"""RaftNode — the host event loop around the batched device step.

This is the TPU-native re-design of the reference's `raftNode`
(reference raft.go:38-273).  Where the reference's 100ms `serveChannels`
loop drives one vendored raft group (raft.go:204-245), this loop drives the
`peer_step` kernel for ALL G groups at once, then performs the host-side
I/O in the reference's exact durability order (raft.go:227-235):

    device step  →  WAL save (entries + hard state)  →  fsync
                 →  transport send                   →  publish commits

so entries are durable before they are sent, and sent before they are
published — invariant §2d.8 of SURVEY.md.

Host responsibilities (the device owns ordering/quorum math only):
  - staging inbound wire records into dense Inbox arrays;
  - mirroring entry payload bytes into storage.PayloadLog, both for local
    proposals (leader) and accepted appends (follower);
  - attaching payloads to outbound AppendEntries requests;
  - proposal forwarding to the current leader hint (the reference gets
    this from etcd/raft's MsgProp routing);
  - apply-at-commit publishing to the commit queue, with the reference's
    replay protocol: every replayed entry is published first, then a
    `None` sentinel marks the channel current (reference raft.go:122-134,
    consumed by db.go:45-52).
"""
from __future__ import annotations

import logging
import os
import queue
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from raftsql_tpu.config import (CANDIDATE, FOLLOWER, LEADER, MSG_REQ,
                                MSG_RESP, NO_VOTE, NO_XFER, PRECANDIDATE,
                                RaftConfig)
from raftsql_tpu.core.state import (install_snapshot_state,
                                    restore_peer_state, set_group_config,
                                    set_peer_progress,
                                    set_transfer_target)
from raftsql_tpu.membership import (MembershipLagError, MembershipManager,
                                    NotLeaderForChange)
from raftsql_tpu.transport.codec import CONF_PREFIX as _CONF_PREFIX, \
    is_conf_entry
from raftsql_tpu.core.step import (IB_NCOLS, INFO_FIELDS, MSG_FIELDS,
                                   peer_step_packed)
from raftsql_tpu.runtime.envelope import (DedupWindow, unwrap,
                                          unwrap_snapshot,
                                          unwrap_snapshot_conf, wrap,
                                          wrap_snapshot,
                                          wrap_snapshot_conf)
from raftsql_tpu.storage.log import PayloadLog
from raftsql_tpu.storage.wal import WAL, split_uniform_runs, wal_exists
from raftsql_tpu.transport.base import (AppendRec, ColRecs, ProposalRec,
                                        SnapshotRec, TickBatch, Transport,
                                        VoteRec)
from raftsql_tpu.utils.metrics import NodeMetrics

log = logging.getLogger("raftsql_tpu.node")

# Commit-queue sentinel marking end-of-stream (the reference closes the
# channel; Python queues need an explicit object).
CLOSED = object()

# Role-code → wire name map for GET /healthz (status()).
_ROLE_NAMES = {FOLLOWER: "follower", CANDIDATE: "candidate",
               LEADER: "leader", PRECANDIDATE: "precandidate"}


class TransferRefused(ValueError):
    """A leadership-transfer request failed validation and was never
    armed: a transfer is already in flight for the group, the target
    already leads, or the target is a learner/non-voter (thesis §3.10
    requires a VOTER target — a learner can never win the election the
    TimeoutNow grant starts).  Subclasses ValueError so the HTTP planes
    answer 400 without a dedicated handler; not-leader refusals raise
    NotLeaderForChange instead (421 + retry hint)."""

    def __init__(self, group: int, why: str):
        super().__init__(f"group {group}: transfer refused: {why}")
        self.group = group
        self.why = why

class _PackedView:
    """Attribute access over columns of a packed numpy array — the
    Outbox/StepInfo face the tick phases consume, backed by free views
    into the ONE array device_get returns (core/step.py packed forms)."""

    def __init__(self, **cols):
        self.__dict__.update(cols)


def _view_outbox(arr: np.ndarray) -> _PackedView:
    v = _PackedView(**{n: arr[:, :, i] for i, n in enumerate(MSG_FIELDS)})
    v.a_ents = arr[:, :, IB_NCOLS:]
    return v


def _view_info(ginfo: np.ndarray, next_idx: np.ndarray) -> _PackedView:
    v = _PackedView(**{n: ginfo[:, i] for i, n in enumerate(INFO_FIELDS)})
    v.noop = v.noop.astype(bool)
    v.app_conflict = v.app_conflict.astype(bool)
    v.next_idx = next_idx
    return v


# Discriminator heading a live publish-phase commit item:
# (RAW_BATCH, group, base_idx, [raw_bytes, ...]).  The queue carries
# several item shapes (see runtime/db.py _expand_commit_item); the raw
# form is the only one whose payloads still need envelope unwrap/dedup,
# so it is tagged explicitly rather than sniffed by payload type.
RAW_BATCH = object()


class _ReadBatch:
    """One group's worth of ReadIndex registrations sharing ONE quorum
    round (RaftNode.read_join).  Client threads join the group's
    pending batch and wait on `evt`; the tick thread stamps (target,
    term, reg) when it promotes the batch into the tick's broadcast,
    and whichever thread first observes the quorum (tick tail or a
    transport delivery) publishes `status` and fires the event."""

    __slots__ = ("group", "count", "target", "term", "reg", "status",
                 "evt")

    def __init__(self, group: int):
        self.group = group
        self.count = 0          # joined readers (metrics batch size)
        self.target = -1        # commit index the batch reads at
        self.term = -1          # leader term the round must confirm
        self.reg = -1           # registration tick (round seq binding)
        self.status = ""        # "" pending | "ok" | "not_leader"
        self.evt = threading.Event()
# Same shape, but payloads are PLAIN bytes — no dedup envelopes (the
# fused/mesh runtimes route proposals on the host and never wrap).
# Expansion skips the per-entry unwrap probe, which is a measurable
# share of the consumer at durable-bench saturation.
RAW_PLAIN = object()
# A whole tick's publishes in ONE queue item:
# (RAW_MANY, [(group, base_idx, [plain_bytes, ...]), ...]).  At G=10k
# saturation the fused publish was one queue.put per ready group
# (~10k/tick, ~100 ms of lock/notify traffic); batching them costs the
# consumer one extra loop level and the producer almost nothing.
RAW_MANY = object()


class RaftNode:
    """One consensus node: G raft groups, one peer row each.

    node_id is 1-based like the reference (raft.go:148-151); the device
    peer axis uses node_id - 1.
    """

    def __init__(self, node_id: int, num_nodes: int, cfg: RaftConfig,
                 transport: Transport, data_dir: str):
        if cfg.num_peers != num_nodes:
            raise ValueError("cfg.num_peers must equal num_nodes")
        self.cfg = cfg
        self.node_id = node_id
        self.self_id = node_id - 1
        self.num_nodes = num_nodes
        # Witness identity (config.py quorum geometry): a witness votes,
        # appends and fsyncs but owns no shard — runtime/db.py reads
        # this flag and installs the discard-only WitnessStateMachine
        # instead of ever invoking the SQLite factory.
        self.witness_self = self.self_id in cfg.witness_set
        self.data_dir = data_dir
        self.transport = transport

        G = cfg.num_groups
        self.commit_q: "queue.Queue" = queue.Queue()
        self.error: Optional[Exception] = None
        self.metrics = NodeMetrics()
        # Host-plane span tracer (raftsql_tpu/obs/spans.py), OFF by
        # default; every hook below gates on it so the disabled tick
        # pays one attribute test (see enable_tracing).
        self.tracer = None

        self._stage_lock = threading.Lock()
        self._stage_votes: Dict[Tuple[int, int], VoteRec] = {}
        self._stage_apps: Dict[Tuple[int, int], AppendRec] = {}
        self._stage_snaps: Dict[int, SnapshotRec] = {}
        # Columnar staging (transport/base.py ColRecs): payload-free
        # messages scatter straight into these [G, P] arrays; record
        # staging (payload appends, and peers speaking the record form)
        # overlays them at inbox-build time.  _stg_a_seq carries the
        # ReadIndex round binding (REQ rows only — a response's seq lives
        # in its sender's numberspace and must never be echoed back).
        # Arrival stamps decide overlay order for mixed delivery forms:
        # each _deliver bumps _arrival once; _stg_a_arr[g, p] is the stamp
        # of the newest COLUMNAR append in the slot, _stage_app_arr the
        # stamp of the staged record — inbox build lets the newer one win,
        # whatever its form ("newest message per (group, src, slot) wins").
        self._stg: np.ndarray = self._fresh_stage_cols()
        self._stg_a_seq = np.zeros((G, num_nodes), np.int64)
        self._stg_a_arr = np.zeros((G, num_nodes), np.int64)
        self._stage_app_arr: Dict[Tuple[int, int], int] = {}
        self._arrival = 0
        # True iff anything was staged since the last inbox build; a
        # clean build reuses the prebuilt all-zero device inbox instead
        # of allocating + converting ~30 arrays per step (at small G the
        # conversions, not the kernel, dominated step cost).
        self._stage_dirty = False
        self._zero_inbox = None          # built lazily (needs jnp)
        self._zero_seq = np.zeros((G, num_nodes), np.int64)

        # InstallSnapshot hooks (wired by the apply layer in resume mode;
        # both unset => full state transfer disabled, catch-up below the
        # compaction floor just logs).  provider(g) -> (applied_idx, blob);
        # installer(g, last_idx, blob) replaces the state machine's state.
        self.snapshot_provider = None
        self.snapshot_installer = None
        self._snap_sent: Dict[Tuple[int, int], int] = {}
        self._snap_due: List[Tuple[int, int, int]] = []
        # Catch-up pacing: (group, dst) -> (next_idx last sent for, tick).
        # Rebuilding + resending the same out-of-window append every tick
        # is pure bandwidth waste; resend only on next_idx progress or
        # after a few ticks without it.
        self._catchup_sent: Dict[Tuple[int, int], Tuple[int, int]] = {}

        self._prop_lock = threading.Lock()
        self._props: List[deque] = [deque() for _ in range(G)]
        # Incremental O(active) bookkeeping for the two per-tick walks
        # that profiled O(G) at G=10k (VERDICT r3 task 4): _prop_len[g]
        # mirrors len(_props[g]) so the tick's prop_n build is one
        # vectorized minimum instead of a 10k-deque generator; _fwd_groups
        # is the set of groups with queued or in-flight-forwarded
        # proposals, so the forwarding walk touches only those.  Both are
        # guarded by _prop_lock, same as the structures they mirror.
        self._prop_len = np.zeros(G, np.int32)
        self._fwd_groups: set = set()
        # Proposals forwarded to a (possibly stale) leader hint, kept as
        # (payload, deadline_tick): if the payload is not observed
        # committed by the deadline, it is re-queued and forwarded again.
        # Without this, a proposal forwarded to a crashed leader is lost
        # and its client hangs forever (the reference inherits the same
        # exposure from etcd/raft's MsgProp forwarding; the batched host
        # plane can do better cheaply).  Commit-observation matches by
        # payload identity — the same content-FIFO quirk as the ack
        # router (SURVEY.md §2d.3).
        self._fwd: List[List[Tuple[bytes, int]]] = [[] for _ in range(G)]
        # Our own proposals accepted into OUR log as leader, still
        # uncommitted: (log_idx, payload).  A deposed (e.g. minority)
        # leader's uncommitted suffix is conflict-truncated by the new
        # leader's first append — without this tracking those proposals
        # vanish and their clients hang forever (the reference loses them
        # the same way through etcd/raft; the envelope dedup makes the
        # requeue-retry safe).  Tick-thread only, no lock.
        self._local: List[List[Tuple[int, bytes]]] = [[] for _ in range(G)]
        self._tick_no = 0

        # Leadership-transfer plane (thesis §3.10, PR 11): one latch per
        # group, armed on the TICK thread (self.state is donated every
        # step; client threads enqueue into _xfer_req instead of
        # patching device state directly).  Deadlines run on the LEASE
        # clock — the same timer units election timeouts count in — so
        # an idle event loop's elided steps cannot stretch a transfer's
        # abort horizon.  _xfer_events is the recent-outcome log flight
        # bundles attach for attribution.
        self._xfer_lock = threading.Lock()
        self._xfer_req: List[Tuple[int, int]] = []
        self._xfer: Dict[int, dict] = {}
        self._xfer_events: deque = deque(maxlen=256)

        self.payload_log = PayloadLog(G)
        # [G] applied index and [G, 3] (term, voted_for, commit) hard-state
        # cache as numpy arrays: every tick compares/updates ALL groups, so
        # these must be vectorized state, not per-group Python objects.
        self._applied = np.zeros(G, np.int64)
        self._prev_role = np.zeros(G, np.int64)     # elections_won metric
        # ReadIndex state (raft §6.4).  Confirmations are bound to
        # request ROUNDS: every append REQ carries this node's tick
        # number (seq); responses echo it.  _resp_echo[g, p] is the
        # newest echoed seq from peer p and _resp_term the term it
        # responded at — a read registered at tick R is quorum-confirmed
        # once enough peers echoed seq >= R at our current term, so a
        # DELAYED pre-registration response can never count.  Role/hint
        # are per-tick host caches (device state is donated; client
        # threads must not touch it).
        self._resp_echo = np.zeros((G, num_nodes), np.int64)
        self._resp_term = np.zeros((G, num_nodes), np.int64)
        self._last_role = np.zeros(G, np.int64)
        self._last_hint = np.full(G, -1, np.int64)
        # Leader-lease clock (config.lease_ticks): leases must be
        # measured in TIMER units (what election timeouts are counted
        # in), not step counts — the event loop runs timer_inc=0 work
        # steps and elides idle steps with timer_inc=k, so steps and
        # timer time diverge freely.  _lease_clock advances with every
        # tick's timer_inc; _round_clock[seq % R] remembers the clock
        # at which round `seq` (= tick number, the seq stamped on
        # outgoing append REQs) went out, so a quorum of seq echoes
        # converts to "a quorum confirmed me at clock c" and the lease
        # runs to c + lease_ticks.  Rounds older than the ring are
        # simply unprovable — the check degrades to ReadIndex.
        self._lease_clock = 0
        self._ROUND_RING = 4096
        self._round_seq = np.full(self._ROUND_RING, -1, np.int64)
        self._round_clock = np.zeros(self._ROUND_RING, np.int64)
        self._dedup = [DedupWindow() for _ in range(G)]
        self._hard_np = np.zeros((G, 3), np.int64)
        self._hard_np[:, 1] = NO_VOTE

        self._stop_evt = threading.Event()
        # Work signal for the event-driven loop (_run): set whenever a
        # proposal, inbound peer batch, or linearizable-read registration
        # arrives, so the next step runs immediately (timer_inc=0)
        # instead of waiting out the tick interval.  The interval-paced
        # steps (timer_inc=1) remain the only ones that advance election
        # and heartbeat timers — real-time raft semantics are unchanged.
        self._work_evt = threading.Event()
        self._stopped = False           # full teardown ran (stop())
        self._thread: Optional[threading.Thread] = None
        self._tick_apps: Dict[Tuple[int, int], AppendRec] = {}
        self._tick_seq = np.zeros((G, num_nodes), np.int64)
        # Serializes the tick's WAL phase against compaction rewrites.
        self._wal_lock = threading.Lock()

        # ---- replay (reference raft.go:122-134 + db.go:27-29 contract).
        self._had_wal = wal_exists(data_dir)
        groups = WAL.replay(data_dir)
        log_terms = {g: [t for (t, _) in gl.entries]
                     for g, gl in groups.items()}
        hard = {g: (gl.hard.term, gl.hard.vote, gl.hard.commit)
                for g, gl in groups.items()}
        starts = {g: (gl.start, gl.start_term) for g, gl in groups.items()}
        self.state = restore_peer_state(cfg, self.self_id, log_terms, hard,
                                        starts=starts)
        for g, gl in groups.items():
            if gl.start:
                self.payload_log.set_start(g, gl.start, gl.start_term)
            self.payload_log.put(g, gl.start + 1,
                                 [d for (_, d) in gl.entries],
                                 [t for (t, _) in gl.entries])
            self._hard_np[g] = (gl.hard.term, gl.hard.vote, gl.hard.commit)
            # Replay publishes the COMMITTED prefix only (then the nil
            # sentinel); the appended-but-uncommitted tail re-publishes
            # through the ordinary commit path once a leader commits it.
            # The reference publishes the WHOLE replayed log
            # (raft.go:130-132) — applying entries a new leader may
            # conflict-truncate: the process-plane chaos harness caught a
            # restarted node keeping such a phantom row in SQLite forever
            # (survivors can then never converge;
            # tests/test_node_loop.py::test_replay_publishes_only_committed_prefix).
            self._applied[g] = min(gl.log_len, gl.hard.commit)
            if gl.dedup is not None:
                # Seed the dedup window from the persisted baseline
                # (storage/wal.py REC_DEDUP) BEFORE replay publishes the
                # retained suffix: the suffix may hold a forward-retry
                # duplicate whose first copy was compacted below the
                # floor — live peers scrub it from their in-memory
                # windows; without the baseline a restarted node would
                # re-apply it and diverge (the snapshot-family chaos
                # sweep caught exactly this).  _decode_entry then layers
                # the above-floor pids on top in index order.
                self._dedup[g].restore(gl.dedup[1])
        self._replay_groups = groups
        self.wal = WAL(data_dir, segment_bytes=cfg.wal_segment_bytes)
        # Re-seed the fresh handle's dedup baseline (it survives only
        # in-memory per handle, like the conf baseline — which
        # _patch_group_config re-seeds the same way): without this, the
        # first segment unlink after a restart could drop the replayed
        # REC_DEDUP record before any new compaction re-writes it.
        for g, gl in groups.items():
            if gl.dedup is not None:
                self.wal.set_dedup(g, gl.dedup[0], gl.dedup[1])
        # Dynamic membership (raftsql_tpu/membership/): always on — a
        # follower must recognize a conf entry the moment the first one
        # ever commits.  Restore the active config from the WAL: the
        # REC_CONF baseline, then conf ENTRIES committed above it, then
        # appended-but-uncommitted ones back into the pending list.
        self.membership = MembershipManager(
            num_nodes, G, initial_voters=cfg.initial_voters,
            write_quorum=cfg.write_quorum,
            election_quorum=cfg.election_quorum,
            witnesses=cfg.witnesses or (),
            unsafe_geometry=cfg.unsafe_quorum_geometry) \
            if num_nodes <= 64 else None
        if self.membership is not None:
            mm = self.membership
            for g, gl in groups.items():
                if mm.restore(g, gl.conf, gl.entries, gl.start,
                              int(self._hard_np[g, 2])):
                    self._patch_group_config(g, durable=False)
        # Leader view cache for the promote catch-up gate ([G, P]
        # next_idx from the last step's StepInfo).
        self._next_idx = np.ones((G, num_nodes), np.int64)
        self._self_arr = jnp.asarray(self.self_id, jnp.int32)
        # timer_inc constants for the step call: index by advance_timers.
        self._ti_arr = (jnp.asarray(0, jnp.int32),
                        jnp.asarray(1, jnp.int32))
        # Device-reported minimum ticks until any timer fires; 1 until
        # the first step reports (see _run / core/step.py timer_margin).
        self._timer_margin = 1
        # One-shot broadcast nudge (core/step.py force_bcast): set by
        # read_index so the ReadIndex confirm round goes out on the next
        # step instead of the next heartbeat.  Benign race: a lost
        # concurrent set only delays the round to the heartbeat.
        # ALWAYS shipped as a [G] bool mask — the batched-ReadIndex
        # promote narrows the nudge per group, and keeping one dtype
        # from the very first tick means one jit entry: a mid-flight
        # scalar->mask switch would recompile the step UNDER the
        # leader's election timer and depose it.
        self._force_bcast = False
        self._fb_arr = (jnp.zeros(G, bool), jnp.ones(G, bool))
        # Batched ReadIndex (PR 12): client threads join a per-group
        # pending batch (read_join); the tick thread promotes every
        # pending batch into ONE shared quorum round — the broadcast the
        # tick already fires — so N concurrent linearizable reads cost
        # one round per tick instead of one round each.  _rb_pending
        # holds the batch joiners may still enter; _rb_active holds
        # promoted batches awaiting their round's quorum of echoes.
        self._rb_lock = threading.Lock()
        self._rb_pending: Dict[int, _ReadBatch] = {}
        self._rb_active: Dict[int, List[_ReadBatch]] = {}

    # ------------------------------------------------------------------
    # lifecycle

    def start(self, threaded: bool = True) -> None:
        """Publish the WAL replay + sentinel, start the transport, and —
        unless threaded=False (benchmarks/tests that drive `tick()`
        manually for deterministic lockstep) — the tick thread."""
        for g, gl in sorted(self._replay_groups.items()):
            # Committed prefix only — see the _applied restore in
            # __init__ for why the uncommitted tail must NOT reach the
            # state machine here.
            upto = max(0, min(gl.log_len, gl.hard.commit) - gl.start)
            for i, (term, data) in enumerate(gl.entries[:upto]):
                sql = self._decode_entry(g, data, gl.start + 1 + i)
                if sql is not None:
                    self.commit_q.put((g, gl.start + 1 + i, sql))
        self._replay_groups = {}
        self.commit_q.put(None)         # replay-complete sentinel
        # Adopt the transport's fault counters into this node's metrics
        # (transports that count — TcpTransport's corrupt-frame drops —
        # carry a `metrics` attribute; /metrics then reports them).
        if hasattr(self.transport, "metrics"):
            self.transport.metrics = self.metrics
        self.transport.start(self.node_id, self._deliver, self._on_error)
        if threaded:
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name=f"raft-node-{self.node_id}")
            self._thread.start()

    def stop(self) -> None:
        # _on_error may have set _stop_evt already (transport failure
        # teardown); the transport/WAL cleanup below must STILL run then —
        # only a completed stop() makes a second call a no-op.
        if self._stopped:
            return
        self._stopped = True
        self._stop_evt.set()
        self._work_evt.set()     # wake a margin-length idle sleep NOW
        self._rb_abort_all()     # unblock batched readers immediately
        if self._thread is not None:
            self._thread.join(timeout=10)
        self.transport.stop()
        self.wal.close()
        self.commit_q.put(CLOSED)

    def _on_error(self, err: Exception) -> None:
        # Transport failure → teardown, error fans out to pending acks
        # (reference raft.go:136-142, db.go:83-95).
        log.error("node %d transport error: %s", self.node_id, err)
        self.error = err
        self._stop_evt.set()
        self._work_evt.set()     # wake a margin-length idle sleep NOW
        self._rb_abort_all()     # unblock batched readers immediately
        self.commit_q.put(CLOSED)

    # ------------------------------------------------------------------
    # client plane

    def enable_tracing(self) -> None:
        """Attach the host-plane span tracer (raftsql_tpu/obs/):
        proposals proposed HERE are followed propose → append →
        replicate → commit (apply/ack stamps come from the RaftDB
        layer).  Idempotent."""
        from raftsql_tpu.obs.spans import SpanTracer
        if self.tracer is None:
            self.tracer = SpanTracer()
        self.wal.obs = self.tracer
        if hasattr(self.transport, "obs"):
            self.transport.obs = self.tracer

    def propose(self, group: int, payload: bytes,
                pid: Optional[int] = None) -> None:
        """Enqueue a proposal; routed to the leader on the next tick.

        The payload is wrapped with a unique envelope id so that
        forward-retries after leader failure apply exactly once
        (runtime/envelope.py).  `pid` pins the envelope id instead of
        drawing a fresh one — the CLIENT-retry token (api/client.py
        X-Raft-Retry-Token): a PUT re-sent across a crash or leader
        failover re-proposes under the same id, and the publish-time
        dedup collapses whichever copies commit to one apply."""
        if not 0 <= group < self.cfg.num_groups:
            raise ValueError(f"group {group} out of range "
                             f"[0, {self.cfg.num_groups})")
        if self.tracer is not None:
            self.tracer.begin(group, payload.decode("utf-8", "replace"))
        with self._prop_lock:
            self._props[group].append(wrap(payload, pid))
            self._prop_len[group] += 1
            self._fwd_groups.add(group)
        self._work_evt.set()

    def propose_many(self, group: int, payloads) -> None:
        """Batch `propose`: one lock hold and envelope pass for a whole
        iterable of payloads (benchmark feeders at G x E per tick would
        otherwise spend the tick budget on lock churn)."""
        if not 0 <= group < self.cfg.num_groups:
            raise ValueError(f"group {group} out of range "
                             f"[0, {self.cfg.num_groups})")
        if self.tracer is not None:
            for p in payloads:
                self.tracer.begin(group, p.decode("utf-8", "replace"))
        wrapped = [wrap(p) for p in payloads]
        with self._prop_lock:
            self._props[group].extend(wrapped)
            self._prop_len[group] += len(wrapped)
            self._fwd_groups.add(group)
        self._work_evt.set()

    def _decode_entry(self, group: int, data: bytes,
                      idx: int = 0) -> Optional[str]:
        """Envelope-aware publish decision: None = skip (empty entry or
        duplicate of an already-applied forwarded proposal).  `idx` is
        the entry's log index — recorded in the dedup window so snapshot
        transfers can ship exactly the window at their applied point."""
        if not data:
            return None
        if data[:1] == _CONF_PREFIX and is_conf_entry(data):
            return None        # membership entry — applied, never SQL
        pid, payload = unwrap(data)
        if pid is not None and self._dedup[group].seen(pid, idx):
            return None
        return payload.decode("utf-8")

    def dedup_for(self, group: int) -> DedupWindow:
        """The group's forward-retry dedup window, for commit-queue
        consumers expanding RAW_BATCH items on their own thread.

        Threading contract (the reason this is an accessor and not a
        reach into _dedup): `seen()` is called by the consumer thread;
        `pairs_upto()`/`restore()` run on the tick thread. DedupWindow
        orders those safely internally; no other methods are
        cross-thread."""
        return self._dedup[group]

    # ------------------------------------------------------------------
    # dynamic membership (raftsql_tpu/membership/)

    def _patch_group_config(self, g: int, durable: bool = True) -> None:
        """Push group g's applied config into the device masks and
        (durable=True) the WAL baseline.  Tick thread (or __init__)."""
        # First conf this node ever sees: leave the static-full-voter
        # fast path so the step reads the masks this patch writes
        # (config.py dynamic_membership; one recompile, conf changes
        # are rare admin events).
        if self.cfg.static_full_voters:
            import dataclasses as _dc
            self.cfg = _dc.replace(self.cfg, dynamic_membership=True)
        mm = self.membership
        vrow, jrow, selfv = mm.device_rows(g, self.self_id)
        self.state = set_group_config(self.state, g, vrow, jrow, selfv)
        c = mm.config(g)
        with self._wal_lock:
            self.wal.set_conf(g, c.index, 0, c.voters, c.joint,
                              c.learners)
        if durable:
            self.metrics.conf_changes_applied += 1

    def propose_conf(self, group: int, entry: bytes) -> None:
        """Queue a conf entry — NO envelope wrap (conf apply is
        idempotent by log index, and the publish plane recognizes conf
        entries by their leading byte; an envelope would hide it)."""
        with self._prop_lock:
            self._props[group].append(entry)
            self._prop_len[group] += 1
            self._fwd_groups.add(group)
        self._work_evt.set()

    def member_change(self, group: int, op: str, peer: int) -> dict:
        """Admin plane: add/remove/promote a peer slot of `group`.

        Accepted at the group's leader only (NotLeaderForChange names
        the hint to retry at); `promote` additionally requires the
        learner to be CAUGHT UP — its replication point within one
        append batch of the leader's commit — so a promotion can never
        stall the new joint quorum behind a cold learner."""
        if self.membership is None:
            raise RuntimeError("membership requires num_peers <= 64")
        if not 0 <= group < self.cfg.num_groups:
            raise ValueError(f"group {group} out of range")
        if self._last_role[group] != LEADER:
            raise NotLeaderForChange(group, self.leader_of(group) + 1)
        if op == "promote":
            commit = int(self._hard_np[group, 2])
            behind = commit - (int(self._next_idx[group, peer]) - 1)
            if behind > self.cfg.max_entries_per_msg:
                raise MembershipLagError(
                    f"group {group}: learner {peer} is {behind} entries "
                    f"behind commit {commit}; let catch-up finish before "
                    "promoting")
        entry = self.membership.make_change(group, op, peer)
        self.propose_conf(group, entry)
        return self.membership.describe(group)

    def members_doc(self) -> dict:
        """GET /members payload: per-group active config + leader."""
        if self.membership is None:
            return {"error": "membership requires num_peers <= 64"}
        out = {}
        for g in range(self.cfg.num_groups):
            d = self.membership.describe(g)
            d["leader"] = self.leader_of(g) + 1      # 1-based, 0 unknown
            out[str(g)] = d
        return {"num_peers": self.num_nodes, "groups": out,
                "witnesses": sorted(self.cfg.witness_set),
                "node": self.node_id}

    def _membership_tick(self, info) -> None:
        """Joint-transition driver: whichever peer currently leads a
        joint group auto-proposes the LEAVE_JOINT (rate-limited), so a
        leader crash between the two phases cannot wedge the group."""
        mm = self.membership
        if mm is None or not mm.joint_groups:
            return
        role = info.role
        for g in list(mm.joint_groups):
            if role[g] == LEADER:
                entry = mm.maybe_leave(g, self._tick_no,
                                       4 * self.cfg.election_ticks)
                if entry is not None:
                    self.propose_conf(g, entry)

    # ------------------------------------------------------------------
    # leadership transfer (raft thesis §3.10, PR 11)

    def transfer_leadership(self, group: int, target: int,
                            deadline_ticks: Optional[int] = None) -> dict:
        """Arm a graceful leadership transfer of `group` to peer slot
        `target` (0-based).  Accepted at the group's leader only; the
        device latch stops proposal intake, waits for the target's
        match_index to catch up, then fires the TimeoutNow grant
        (core/step.py Phase 9).  One transfer in flight per group; the
        host aborts and re-opens intake after `deadline_ticks` of lease
        clock (default 4 election timeouts).  Client-thread safe: the
        latch is armed by the tick thread."""
        cfg = self.cfg
        if not 0 <= group < cfg.num_groups:
            raise ValueError(f"group {group} out of range")
        if not 0 <= target < cfg.num_peers:
            raise ValueError(f"target {target} out of peer-slot range")
        if self._last_role[group] != LEADER:
            self.metrics.transfers_refused += 1
            raise NotLeaderForChange(group, self.leader_of(group) + 1)
        if target == self.self_id:
            self.metrics.transfers_refused += 1
            raise TransferRefused(group, "target already leads")
        if self.membership is not None \
                and not self.membership.is_voter(group, target):
            self.metrics.transfers_refused += 1
            raise TransferRefused(
                group, f"peer {target} is a learner/non-voter")
        if target in cfg.witness_set:
            # Witnesses vote and persist but never lead (config.py
            # quorum geometry): handing one the lease would strand the
            # group — the device gate (core/step.py Phase 1b) would eat
            # the TimeoutNow and the transfer would stall to deadline.
            self.metrics.transfers_refused += 1
            raise TransferRefused(group, f"peer {target} is a witness")
        dl = int(deadline_ticks) if deadline_ticks \
            else 4 * cfg.election_ticks
        with self._xfer_lock:
            if group in self._xfer:
                self.metrics.transfers_refused += 1
                raise TransferRefused(group, "transfer already in flight")
            self._xfer[group] = {"target": target, "from": self.self_id,
                                 "start_tick": self._tick_no,
                                 "deadline_ticks": dl, "deadline": None,
                                 "armed": False}
            self._xfer_req.append((group, target))
        self.metrics.transfers_initiated += 1
        self._work_evt.set()
        return {"group": group, "from": self.node_id,
                "target": target + 1, "deadline_ticks": dl}

    def _transfer_tick(self, info) -> None:
        """Per-tick transfer driver (tick thread): arm queued requests
        into device state, detect completion (we were deposed and the
        hint names the target), and abort past-deadline transfers by
        clearing the latch — which re-opens the group for proposals on
        the very next step."""
        if not (self._xfer or self._xfer_req):
            return
        with self._xfer_lock:
            reqs, self._xfer_req = self._xfer_req, []
            for (g, tgt) in reqs:
                self.state = set_transfer_target(self.state, g, tgt)
                tr = self._xfer.get(g)
                if tr is not None:
                    tr["armed"] = True
                    tr["deadline"] = (self._lease_clock
                                      + tr["deadline_ticks"])
            role = info.role
            hint = info.leader_hint
            for g, tr in list(self._xfer.items()):
                if not tr["armed"]:
                    continue
                outcome = None
                h = int(hint[g])
                if role[g] != LEADER and h == tr["target"]:
                    outcome = "completed"
                elif self._lease_clock >= tr["deadline"]:
                    # Deadline: leadership never settled on the target.
                    # If we still lead, drop the latch so intake
                    # re-opens; if we were deposed elsewhere the latch
                    # already self-cleared.
                    if role[g] == LEADER:
                        self.state = set_transfer_target(
                            self.state, g, NO_XFER)
                    outcome = "aborted"
                elif role[g] != LEADER and 0 <= h != tr["target"]:
                    outcome = "aborted"    # someone else won
                if outcome is None:
                    continue
                del self._xfer[g]
                stall = self._tick_no - tr["start_tick"]
                if outcome == "completed":
                    self.metrics.transfers_completed += 1
                else:
                    self.metrics.transfers_aborted += 1
                self.metrics.note_transfer_stall(stall)
                self._xfer_events.append(
                    {"group": g, "from": tr["from"] + 1,
                     "to": tr["target"] + 1, "outcome": outcome,
                     "stall_ticks": int(stall), "tick": self._tick_no})

    def transferring_groups(self) -> set:
        """Groups with a leadership transfer in flight (hot-groups
        `transferring` flag)."""
        with self._xfer_lock:
            return set(self._xfer)

    def transfers_doc(self) -> dict:
        """In-flight latches + the recent-outcome log (flight bundles,
        `GET /metrics` debugging)."""
        with self._xfer_lock:
            inflight = {str(g): {"target": tr["target"] + 1,
                                 "from": tr["from"] + 1,
                                 "start_tick": tr["start_tick"]}
                        for g, tr in self._xfer.items()}
            recent = list(self._xfer_events)
        return {"in_flight": inflight, "recent": recent}

    def leader_of(self, group: int) -> int:
        """Last known leader (0-based peer), -1 if unknown.

        Served from the host-side per-tick cache: `self.state` is DONATED
        to the jitted step every tick, so touching the live device array
        from a client thread races buffer invalidation ("Array has been
        deleted")."""
        return int(self._last_hint[group])

    def status(self) -> dict:
        """Per-group consensus status for GET /healthz: role, last known
        leader (1-based, 0 unknown), term, and commit index.  Reads only
        the host-side per-tick caches (same client-thread contract as
        leader_of) — a readiness probe must never touch device state."""
        roles = self._last_role.tolist()
        hints = self._last_hint.tolist()
        hard = self._hard_np
        return {
            str(g): {"role": _ROLE_NAMES.get(roles[g], "unknown"),
                     "leader": hints[g] + 1,
                     "term": int(hard[g, 0]),
                     "commit": int(hard[g, 2])}
            for g in range(self.cfg.num_groups)}

    # ------------------------------------------------------------------
    # linearizable reads (ReadIndex, raft §6.4 — beyond the reference's
    # stale-local-read model, db.go:128-130)

    # "No evidence" filler for the lease quorum sort: far below any
    # reachable lease clock, so a peer with no provable confirmation
    # can never contribute a lease-extending stamp (0 would alias the
    # boot-time clock and grant phantom boot leases).
    _NO_LEASE_CLOCK = -(1 << 40)

    def commit_watermark(self, group: int) -> int:
        """This node's current commit index for `group` — the
        replicated read-index watermark follower/session reads wait
        on (X-Raft-Session).  Host cache only; safe from any thread."""
        return int(self._hard_np[group, 2])

    def _lease_eval(self, group: int) -> Optional[Tuple[int, int]]:
        """(commit, remaining_ticks) of this node's leader lease for
        `group`, or None when no lease can be proved at all (leases
        disabled, not leader, §6.4 current-term-commit precondition
        pending).  remaining_ticks <= 0 means the lease has lapsed.

        The lease: each peer's newest seq echo at our current term
        names the newest round it confirmed; mapping seqs to the lease
        clock they departed at and taking the quorum-th largest gives
        the latest clock c at which a full quorum had confirmed our
        leadership (and, by the Phase-8 reset + prevote in-lease rule,
        cannot grant an election probe before c + election_ticks of
        its own clock).  Validity bound: now + max_clock_skew <
        c + lease_ticks."""
        cfg = self.cfg
        if cfg.lease_ticks <= 0 or self._last_role[group] != LEADER:
            return None
        term = int(self._hard_np[group, 0])
        commit = int(self._hard_np[group, 2])
        # try_term_of: client threads race the compactor; degrade, not
        # assert (same contract as read_index).
        if commit < 1 \
                or self.payload_log.try_term_of(group, commit) != term:
            return None
        with self._stage_lock:
            echo = self._resp_echo[group].copy()
            rterm = self._resp_term[group].copy()
        R = self._ROUND_RING
        clocks = np.full(self.num_nodes, self._NO_LEASE_CLOCK, np.int64)
        now = int(self._lease_clock)
        for p in range(self.num_nodes):
            if p == self.self_id:
                continue
            s = int(echo[p])
            if s <= 0 or int(rterm[p]) != term:
                continue
            if int(self._round_seq[s % R]) == s:
                clocks[p] = self._round_clock[s % R]
        clocks[self.self_id] = now
        mm = self.membership
        if mm is not None and not mm.is_default(group):
            q = mm.quorum_nth(group, clocks)
        else:
            # Lease evidence is WRITE-quorum evidence (append acks):
            # under flexible geometry the election quorum intersects
            # every write quorum, so write_size acks fence elections.
            q = int(np.sort(clocks)[self.num_nodes - cfg.write_size])
        return commit, (q + cfg.lease_ticks) - (now + cfg.max_clock_skew)

    def lease_read(self, group: int) -> Optional[int]:
        """Serve a linearizable read from the leader lease: returns the
        read's target commit index, or None when no valid lease covers
        `now + max_clock_skew` (the caller degrades to the ReadIndex
        round — never a silent stale read)."""
        ev = self._lease_eval(group)
        if ev is None:
            return None
        commit, remaining = ev
        if remaining > 0:
            self.metrics.lease_grants += 1
            return commit
        self.metrics.lease_expiries += 1
        return None

    # Cap on how far ahead a published lease deadline may reach: the
    # shm publisher refreshes every millisecond or two, so a short
    # horizon costs no availability while bounding how stale a mapped
    # deadline can be if tick pacing stalls right after a publish.
    _LEASE_HORIZON_S = 0.05

    def lease_deadline_s(self, group: int) -> float:
        """The time.monotonic() instant until which a lease read for
        `group` is provably safe, or 0.0 when no live lease.  This is
        the routing-hint / shm-snapshot surface (runtime/shm.py): the
        remaining lease ticks — already net of max_clock_skew, the
        same bound lease_read enforces — convert to wall time at the
        configured tick interval, capped at _LEASE_HORIZON_S.
        CLOCK_MONOTONIC is system-wide on Linux, so worker processes
        compare the published deadline against their own clock.  No
        metric side effects (this is a telemetry probe, not a served
        read)."""
        ev = self._lease_eval(group)
        if ev is None:
            return 0.0
        _commit, remaining = ev
        if remaining <= 0:
            return 0.0
        interval = max(self.cfg.tick_interval_s, 1e-4)
        return time.monotonic() + min(remaining * interval,
                                      self._LEASE_HORIZON_S)

    def read_index(self, group: int):
        """Register a linearizable read.

        Returns (target_index, registration_tick) when this node leads
        the group AND its commit covers an entry of its CURRENT term —
        raft §6.4's precondition: a fresh leader's commit index may
        still trail entries an earlier leader acked, until its own
        no-op commits.  Returns () when leading but that precondition
        is pending (caller should poll), or None when not leading
        (caller should redirect to `leader_of`)."""
        if self._last_role[group] != LEADER:
            return None
        # Nudge a broadcast round out on the next step: the quorum
        # confirmation (and, while the precondition is pending, the
        # no-op's replication) must not wait for the heartbeat interval.
        self._force_bcast = True
        self._work_evt.set()
        commit = int(self._hard_np[group, 2])
        term = int(self._hard_np[group, 0])
        # try_term_of: this runs on CLIENT threads racing the tick thread
        # and the compactor — a stale commit cache below the compaction
        # floor must degrade to "retry", not an assertion.
        if commit < 1 \
                or self.payload_log.try_term_of(group, commit) != term:
            return ()
        # The read's target is the leader's current commit index; the
        # quorum round that follows confirms no newer leader could have
        # committed past it before registration.  reg = tick_no + 1:
        # only rounds SENT strictly after this registration may confirm
        # it (a send earlier in the in-flight tick predates the commit
        # snapshot just taken).
        return commit, self._tick_no + 1

    def read_ready(self, group: int, reg_tick: int) -> bool:
        """True once a quorum confirmed our leadership on rounds STARTED
        at/after the registration: peers must have echoed a request seq
        >= reg_tick while at our current term.  Echo binding (not tick
        arithmetic) means a response delayed in flight from before the
        registration can never count.

        The (echo, term) pair is written under _stage_lock; reading
        under the same lock keeps the pairing consistent — a torn read
        could pair a new rejection's seq with the previous echo's term
        and count a deposing peer as a confirmation."""
        term = int(self._hard_np[group, 0])
        with self._stage_lock:
            echo = self._resp_echo[group].copy()
            rterm = self._resp_term[group].copy()
        ok = (echo >= reg_tick) & (rterm == term)
        mm = self.membership
        if mm is not None and not mm.is_default(group):
            # Mask-weighted confirmation (joint: both majorities).
            return mm.quorum_confirmed(group, ok, self.self_id)
        # ReadIndex confirmation is write-quorum sized: any election
        # quorum intersects it, so a confirmed round proves no newer
        # leader committed past the registration snapshot.
        return int(ok.sum()) + 1 >= self.cfg.write_size

    # ------------------------------------------------------------------
    # batched ReadIndex (PR 12): all linearizable reads registered
    # between two ticks share the ONE broadcast round the next tick
    # fires, so quorum cost is per-tick, not per-read.

    def read_join(self, group: int) -> Optional[_ReadBatch]:
        """Join the group's pending ReadIndex batch.  Returns a
        _ReadBatch whose `evt` fires once the shared round resolves —
        status "ok" with `target` the commit index to wait on, or
        "not_leader" (re-join or redirect via leader_of).  Returns
        None when this node does not currently lead the group.

        Unlike read_index, no commit snapshot is taken here: the tick
        thread stamps the batch's target at promotion, where commit
        state is frozen (commits only advance on that thread) and the
        confirming round is sent strictly afterwards."""
        if self._last_role[group] != LEADER:
            return None
        with self._rb_lock:
            b = self._rb_pending.get(group)
            if b is None:
                b = _ReadBatch(group)
                self._rb_pending[group] = b
            b.count += 1
        self._work_evt.set()     # promote on a prompt tick, not a timer
        return b

    def _rb_finish(self, b: _ReadBatch, status: str) -> bool:
        """Claim + publish a batch outcome exactly once; False when
        another thread already resolved it (the tick tail and transport
        deliveries race — metrics must count each batch once)."""
        with self._rb_lock:
            if b.status:
                return False
            b.status = status
            if self._rb_pending.get(b.group) is b:
                del self._rb_pending[b.group]
            lst = self._rb_active.get(b.group)
            if lst is not None:
                try:
                    lst.remove(b)
                except ValueError:
                    pass
                if not lst:
                    del self._rb_active[b.group]
        b.evt.set()
        return True

    def _rb_promote(self) -> List[int]:
        """Promote pending batches into this tick's broadcast (tick
        thread ONLY, before the device step: commits advance only on
        this thread, so the (term, commit) snapshot below is frozen,
        and this tick's round — seq = _tick_no — is sent strictly
        after it; that ordering is what makes reg = _tick_no a sound
        registration).  Returns every group whose broadcast must fire
        this tick: freshly promoted batches, batches still waiting on
        the §6.4 no-op, and active batches re-nudged against loss."""
        with self._rb_lock:
            pend = dict(self._rb_pending)
            groups = set(self._rb_active)
        for g, b in pend.items():
            if self._last_role[g] != LEADER:
                self._rb_finish(b, "not_leader")
                continue
            term = int(self._hard_np[g, 0])
            commit = int(self._hard_np[g, 2])
            if commit < 1 \
                    or self.payload_log.try_term_of(g, commit) != term:
                # §6.4 precondition pending: keep the batch joinable —
                # the round this tick fires replicates the no-op whose
                # commit clears the precondition for a later promote.
                groups.add(g)
                continue
            with self._rb_lock:
                if b.status:
                    continue
                if self._rb_pending.get(g) is b:
                    del self._rb_pending[g]     # cut off new joiners
                b.target = commit
                b.term = term
                b.reg = self._tick_no
                self._rb_active.setdefault(g, []).append(b)
            groups.add(g)
        return sorted(groups)

    def _rb_resolve(self) -> None:
        """Resolve active batches whose round completed: called from
        the tick tail and from _deliver (a peer echo may complete the
        quorum between ticks).  Never called under _stage_lock —
        read_ready re-takes it."""
        with self._rb_lock:
            if not self._rb_active:
                return
            items = [b for bs in self._rb_active.values() for b in bs]
        m = self.metrics
        for b in items:
            if b.status:
                continue
            g = b.group
            if self._last_role[g] != LEADER \
                    or int(self._hard_np[g, 0]) != b.term:
                self._rb_finish(b, "not_leader")
            elif self.read_ready(g, b.reg):
                if self._rb_finish(b, "ok"):
                    m.reads_read_index_batched += b.count
                    m.note_read_batch(b.count)

    def _rb_abort_all(self) -> None:
        """Fail every outstanding batch (node stopping): waiting client
        threads must unblock now, not at their deadlines."""
        with self._rb_lock:
            batches = list(self._rb_pending.values()) \
                + [b for bs in self._rb_active.values() for b in bs]
        for b in batches:
            self._rb_finish(b, "not_leader")

    # ------------------------------------------------------------------
    # log compaction (snapshot-resume mode, SURVEY.md §5.4 improvement)

    def compact(self, applied: Dict[int, int], keep: int = 256) -> bool:
        """Drop log prefixes covered by state-machine snapshots.

        `applied[g]` is the index durably applied by the snapshot-capable
        state machine.  Entries up to min(applied, commit) - keep are
        dropped from the payload log, COMPACT floor markers are appended
        to the WAL's active segment, and whole closed segments below
        every floor are unlinked (storage/wal.py compact) — never a
        stop-the-world rewrite of live data, so the tick's WAL phase is
        blocked only for the marker appends + unlinks.  The retained
        `keep` window lets slow followers catch up from the payload log;
        beyond it, the leader ships a full state transfer
        (InstallSnapshot, _send_phase).

        Returns True if anything was compacted.
        """
        # Never compact into the device ring window: the ordinary send
        # path slices payloads for any in-window prev index.
        keep = max(keep, self.cfg.log_window)
        with self._wal_lock:
            changed = False
            floors: Dict[int, Tuple[int, int]] = {}
            for g in range(self.cfg.num_groups):
                commit = int(self._hard_np[g, 2])
                floor = min(applied.get(g, 0), commit,
                            int(self._applied[g])) - keep
                if floor > self.payload_log.start(g):
                    # Persist the dedup window at the new floor FIRST:
                    # the pids at or below it become unrecoverable from
                    # the log the moment the prefix drops, and a replay
                    # without them re-applies any forward-retry
                    # duplicate retained above the floor (REC_DEDUP,
                    # storage/wal.py).  Rides the compaction barrier
                    # (wal.compact syncs after its markers).
                    self.wal.set_dedup(
                        g, floor, self._dedup[g].pairs_upto(floor))
                    self.payload_log.compact(
                        g, floor, self.payload_log.term_of(g, floor))
                    changed = True
                s = self.payload_log.start(g)
                if s > 0:
                    floors[g] = (s, self.payload_log.term_of(g, s))
            if not changed:
                return False
            hard = {g: tuple(int(x) for x in self._hard_np[g])
                    for g in range(self.cfg.num_groups)}
            self.wal.compact(floors, hard)
            self.metrics.compactions += 1
            return True

    # ------------------------------------------------------------------
    # transport plane

    # Column index per field in the packed [G, P, IB_NCOLS+E] staging
    # buffer (core/step.py MSG_FIELDS order; a_ents in the trailing E).
    _COL = {n: i for i, n in enumerate(MSG_FIELDS)}

    def _fresh_stage_cols(self) -> np.ndarray:
        G, P, E = (self.cfg.num_groups, self.num_nodes,
                   self.cfg.max_entries_per_msg)
        return np.zeros((G, P, IB_NCOLS + E), np.int32)

    def _stage_cols(self, src0: int, c) -> None:
        """Scatter one ColRecs into the packed staging buffer
        (stage-lock held).

        Row validation is one vectorized mask (bad groups dropped, same
        contract as the record path)."""
        G = self.cfg.num_groups
        C = self._COL
        if c.n_votes():
            m = (c.v_group >= 0) & (c.v_group < G)
            g = c.v_group[m]
            s = self._stg
            s[g, src0, C["v_type"]] = c.v_type[m]
            s[g, src0, C["v_term"]] = c.v_term[m]
            s[g, src0, C["v_last_idx"]] = c.v_last_idx[m]
            s[g, src0, C["v_last_term"]] = c.v_last_term[m]
            s[g, src0, C["v_granted"]] = c.v_granted[m]
        if c.n_appends():
            m = (c.a_group >= 0) & (c.a_group < G)
            g = c.a_group[m]
            s = self._stg
            s[g, src0, C["a_type"]] = c.a_type[m]
            s[g, src0, C["a_term"]] = c.a_term[m]
            s[g, src0, C["a_prev_idx"]] = c.a_prev_idx[m]
            s[g, src0, C["a_prev_term"]] = c.a_prev_term[m]
            s[g, src0, C["a_commit"]] = c.a_commit[m]
            s[g, src0, C["a_success"]] = c.a_success[m]
            s[g, src0, C["a_match"]] = c.a_match[m]
            self._stg_a_arr[g, src0] = self._arrival
            seq = c.a_seq[m]
            # Seq is the ReadIndex round binding: only REQ rows may set
            # it (we echo the seq of the request we answer).  A response
            # row's seq is the SENDER's tick number — writing it here
            # last-writer-wins could inflate the echo past rounds the
            # peer ever sent, letting read_ready() confirm a ReadIndex
            # with no real quorum round (stale linearizable read).
            req = c.a_type[m] == MSG_REQ
            if req.any():
                self._stg_a_seq[g[req], src0] = seq[req]
            # ReadIndex round bookkeeping for columnar responses.
            rm = (c.a_type[m] == MSG_RESP) & (seq > 0)
            if rm.any():
                rg = g[rm]
                newer = seq[rm] > self._resp_echo[rg, src0]
                rg2 = rg[newer]
                self._resp_echo[rg2, src0] = seq[rm][newer]
                self._resp_term[rg2, src0] = c.a_term[m][rm][newer]

    def _deliver(self, src: int, batch: TickBatch) -> None:
        """Stage inbound records; newest message per (group, src, slot)
        wins, mirroring the dense Inbox overwrite semantics.

        Records that don't fit this node's configuration (unknown group,
        oversized entry batch, bad src) are dropped, not fatal: a
        misconfigured or malicious peer must not tear down this node
        (cf. the reference trusting rafthttp framing, raft.go:268-270)."""
        G, E = self.cfg.num_groups, self.cfg.max_entries_per_msg
        src0 = src - 1
        if not (0 <= src0 < self.num_nodes) or src0 == self.self_id:
            log.warning("node %d: dropping batch from bad src %d",
                        self.node_id, src)
            return
        with self._stage_lock:
            self._arrival += 1
            arrival = self._arrival
            if batch.cols is not None or batch.votes or batch.appends \
                    or batch.snapshots:
                self._stage_dirty = True
            if batch.cols is not None:
                self._stage_cols(src0, batch.cols)
            for v in batch.votes:
                if 0 <= v.group < G:
                    self._stage_votes[(v.group, src0)] = v
            for a in batch.appends:
                if 0 <= a.group < G and a.n <= E \
                        and len(a.payloads) in (0, a.n):
                    self._stage_apps[(a.group, src0)] = a
                    self._stage_app_arr[(a.group, src0)] = arrival
                    if a.type == MSG_RESP and a.seq:
                        # ReadIndex round bookkeeping: newest request-seq
                        # this peer has answered, and at what term.
                        if a.seq > self._resp_echo[a.group, src0]:
                            self._resp_echo[a.group, src0] = a.seq
                            self._resp_term[a.group, src0] = a.term
            for s in batch.snapshots:
                if 0 <= s.group < G:
                    old = self._stage_snaps.get(s.group)
                    if old is None or s.last_idx > old.last_idx:
                        self._stage_snaps[s.group] = s
        if batch.proposals:
            with self._prop_lock:
                for pr in batch.proposals:
                    if 0 <= pr.group < G:
                        self._props[pr.group].append(pr.payload)
                        self._prop_len[pr.group] += 1
                        self._fwd_groups.add(pr.group)
        # This delivery may have carried the echo that completes an
        # active read batch's quorum — resolve NOW (sub-tick read
        # latency), outside _stage_lock (read_ready re-takes it).
        if self._rb_active:
            self._rb_resolve()
        self._work_evt.set()

    # ------------------------------------------------------------------
    # the event loop

    def _run(self) -> None:
        """Event-driven loop with step elision.

        Three kinds of wakeup:
          - WORK (the _work_evt fires): proposals or peer batches
            arrived — step immediately, carrying any timer advance
            accumulated so far (timer_inc = pending).
          - TIMER (interval elapsed): accumulate one tick of timer
            advance; only run a step once the accumulated advance
            reaches the device-reported margin (info.timer_margin — the
            soonest any election/heartbeat timer could fire).  An idle
            node therefore steps about once per heartbeat interval, not
            once per tick interval.
          - STOP.

        The interval-paced timer advance keeps the reference's
        real-time raft semantics (100 ms Tick() cadence, raft.go:207);
        work steps with timer_inc=0 only accelerate message/proposal
        processing between timer boundaries."""
        prof_dir = os.environ.get("RAFTSQL_PROFILE")
        prof = None
        if prof_dir:                     # tick-thread cProfile (§5.1)
            import cProfile
            prof = cProfile.Profile()
            prof.enable()
            prof_path = os.path.join(
                prof_dir, f"raftsql-node{self.node_id}-tick.prof")
            prof_next = time.monotonic() + 5.0
        interval = self.cfg.tick_interval_s
        anchor = time.monotonic()        # last instant pending was credited
        pending = 1                      # first step advances timers
        while not self._stop_evt.is_set():
            if prof is not None and time.monotonic() >= prof_next:
                prof.disable()
                try:
                    prof.dump_stats(prof_path)
                except OSError as e:   # diagnostics must not kill ticks
                    log.warning("profile dump failed: %s", e)
                    prof = None
                else:
                    prof.enable()
                    prof_next = time.monotonic() + 5.0
            now = time.monotonic()
            if interval > 0:
                k = int((now - anchor) / interval)
                if k > 0:
                    # Cap at the margin: after a host stall, elapsed
                    # real time beyond the soonest possible timer fire
                    # must not replay as a burst of catch-up advances
                    # (a timer fires at most once per step anyway).
                    pending = min(pending + k, max(self._timer_margin, 1))
                    anchor += k * interval
                    if anchor < now - interval:
                        anchor = now
            else:
                pending = 1              # untimed config: step each loop
            if self._work_evt.is_set() or pending >= self._timer_margin \
                    or interval <= 0:
                # Clear BEFORE the step: work staged after this point
                # leaves the event set and the wait below returns
                # immediately; work staged before it is consumed by
                # this step.
                self._work_evt.clear()
                try:
                    self.tick(timer_inc=pending)
                except Exception as e:   # pragma: no cover - defensive
                    log.exception("node %d tick failed", self.node_id)
                    self._on_error(e)
                    return
                pending = 0
            # Sleep until the accumulated advance could reach the margin
            # (one heartbeat/election horizon away), or work arrives.
            need = max(self._timer_margin - pending, 1)
            wait = (anchor + need * interval) - time.monotonic()
            if wait > 0:
                self._work_evt.wait(wait)

    def tick(self, advance_timers: bool = True,
             timer_inc: Optional[int] = None) -> None:
        """One full consensus tick: stage → step → WAL → send → publish.

        `timer_inc` is how many tick intervals of election/heartbeat
        timer advance this step applies (see core/step.py); the event
        loop passes its accumulated count.  The boolean shorthand
        `advance_timers` (used by tests and direct drivers) means
        timer_inc=1/0.

        Each phase's wall time accumulates into NodeMetrics (exported via
        GET /metrics as per-tick averages — SURVEY.md §5.1's live-runtime
        profiling), so a slow tick localizes to device step vs WAL fsync
        vs transport vs publish without a profiler attached."""
        if timer_inc is None:
            timer_inc = 1 if advance_timers else 0
        cfg = self.cfg
        G, P, E = cfg.num_groups, cfg.num_peers, cfg.max_entries_per_msg
        m = self.metrics

        # Lease round bookkeeping: this tick's outgoing REQs carry
        # seq = _tick_no; remember the lease clock they depart at
        # (clock first, seq second — a torn cross-thread read then
        # fails the seq match and degrades, never inflates a lease).
        slot = self._tick_no % self._ROUND_RING
        self._round_clock[slot] = self._lease_clock
        self._round_seq[slot] = self._tick_no
        self._lease_clock += timer_inc

        # Staging (snapshot installs + inbox build) is timed separately
        # from the device step — a multi-MB install must not read as "the
        # JAX step got slow" in /metrics.
        ts = time.monotonic()
        self._install_snapshots()
        inbox, tick_apps = self._build_inbox()
        self._tick_apps = tick_apps

        with self._prop_lock:
            prop_n = np.minimum(self._prop_len, E)
        t0 = time.monotonic()
        m.t_stage_ms += (t0 - ts) * 1e3

        # Promote pending ReadIndex batches into this tick's round and
        # build the force-broadcast [G] mask: the legacy whole-node
        # nudge (read_index) broadcasts everywhere — bitwise what the
        # old scalar True did — while batch work narrows the nudge to
        # just the groups with reads in flight.  The idle path reuses
        # the cached all-False mask: no per-tick allocation, and the
        # step's trajectory is bit-identical to the pre-batcher code.
        rb_groups = self._rb_promote() \
            if (self._rb_pending or self._rb_active) else []
        fb = self._force_bcast
        if fb:
            self._force_bcast = False
        if fb or not rb_groups:
            fb_arg = self._fb_arr[fb]
        else:
            fb_mask = np.zeros(G, bool)
            fb_mask[rb_groups] = True
            fb_arg = jnp.asarray(fb_mask)
        state, pob, pinfo, nidx, margin = peer_step_packed(
            cfg, self.state, inbox, jnp.asarray(prop_n), self._self_arr,
            self._ti_arr[timer_inc] if timer_inc <= 1
            else jnp.asarray(timer_inc, jnp.int32),
            fb_arg)
        self.state = state
        pob, pinfo, nidx, margin = jax.device_get(
            (pob, pinfo, nidx, margin))
        outbox = _view_outbox(pob)
        info = _view_info(pinfo, nidx)
        self._next_idx = nidx           # promote catch-up gate cache
        self._timer_margin = max(int(margin), 1)
        t1 = time.monotonic()

        with self._wal_lock:
            self._wal_phase(info)       # durable …
        t2 = time.monotonic()
        self._send_phase(outbox, info)  # … before sent …
        t3 = time.monotonic()
        self._publish_phase(info)       # … before published.
        self._membership_tick(info)     # joint-transition driver
        self._transfer_tick(info)       # leadership-transfer driver
        t4 = time.monotonic()
        m.t_device_ms += (t1 - t0) * 1e3
        m.t_wal_ms += (t2 - t1) * 1e3
        m.t_send_ms += (t3 - t2) * 1e3
        m.t_publish_ms += (t4 - t3) * 1e3
        role = np.asarray(info.role)
        m.elections_won += int(((role == LEADER)
                                & (self._prev_role != LEADER)).sum())
        self._prev_role = role
        self._last_role = role
        self._last_hint = np.asarray(info.leader_hint)
        self._tick_no += 1
        m.ticks += 1
        # Resolve read batches against the freshest role/echo state:
        # covers quorum=1 (read_ready is immediately true) and role
        # loss; multi-node quorums usually resolve from _deliver when
        # the round's echoes arrive.
        if self._rb_active:
            self._rb_resolve()
        # Re-arm the loop when a leader still has proposal backlog past
        # the per-step E cap (progress was made, more to drain now); a
        # leaderless backlog must NOT spin — it drains once election
        # timers (interval-paced) produce a leader.
        if int(np.asarray(info.prop_accepted).sum()) > 0:
            with self._prop_lock:
                leftover = int(self._prop_len.sum()) > 0
            if leftover:
                self._work_evt.set()

    # -- tick phases -----------------------------------------------------

    def _install_snapshots(self) -> None:
        """Apply staged InstallSnapshot transfers (receiver side).

        Only installs strictly ahead of both the local applied point and
        the device commit — snapshots carry committed state, so this
        never regresses; stale/duplicate transfers are dropped.
        """
        if self.snapshot_installer is None:
            # The apply layer registers the installer shortly after node
            # start; keep transfers staged instead of dropping them so a
            # snapshot arriving in that boot window still installs.
            return
        with self._stage_lock:
            snaps, self._stage_snaps = self._stage_snaps, {}
        if not snaps:
            return
        commit = term = None
        for g, rec in snaps.items():
            if commit is None:
                commit = np.asarray(self.state.commit)
                # Writable copy: adopted terms are folded back in so a
                # second staged snapshot for the same group sees them.
                term = np.array(self.state.term)
            if rec.term < int(term[g]):
                # Raft: reject any RPC whose term < currentTerm — a
                # delayed transfer from a deposed leader must not demote
                # a current-term leader or truncate its tail.
                continue
            if rec.term > int(term[g]):
                # A valid higher-term RPC steps this group down on
                # RECEIPT (raft §5.1), even if the transfer itself turns
                # out to be a duplicate or corrupt below.
                st = self.state
                self.state = st._replace(
                    term=st.term.at[g].set(rec.term),
                    voted_for=st.voted_for.at[g].set(NO_VOTE),
                    role=st.role.at[g].set(FOLLOWER),
                    votes=st.votes.at[g].set(False))
                term[g] = rec.term
            if rec.last_idx <= max(self._applied[g], int(commit[g])):
                continue
            conf, inner = unwrap_snapshot_conf(rec.blob)
            pairs, sm_blob = unwrap_snapshot(inner)
            try:
                self.snapshot_installer(g, rec.last_idx, sm_blob)
            except Exception as e:
                # A corrupt/truncated transfer must not tear down the
                # node (cf. the _deliver contract); drop it — the leader
                # re-sends after its cooldown.
                log.warning("node %d g%d: snapshot install failed (%s); "
                            "dropped", self.node_id, g, e)
                continue
            # Counted at SM-install time: observers (tests, operators)
            # see the data the moment the state machine has it, while the
            # device-state patch below may still be compiling.
            self.metrics.snapshots_installed += 1
            if pairs is not None:
                # Adopt the sender's dedup window at the transfer point,
                # keeping exactly-once across the state jump.
                self._dedup[g].restore(pairs)
            # The whole install — payload-log reset, WAL marker, device
            # patch, applied floor — is one atomic unit vs. compact()'s
            # multi-call read of the payload log (it holds _wal_lock for
            # its image build); a reset racing that read corrupts the
            # rewritten WAL.
            with self._wal_lock:
                self.payload_log.reset(g, rec.last_idx, rec.last_term)
                self.wal.set_snapshot(g, rec.last_idx, rec.last_term)
                if pairs is not None:
                    # The adopted window must survive a restart too: the
                    # skipped log range below the install boundary can
                    # hold first copies of duplicates retained above it.
                    self.wal.set_dedup(g, rec.last_idx, pairs)
                self.wal.sync()
                self.state = install_snapshot_state(
                    self.state, g, rec.last_idx, rec.last_term, rec.term)
                self._applied[g] = rec.last_idx
            if conf is not None and self.membership is not None:
                # Adopt the sender's active config at the transfer
                # point (the skipped log range may contain the conf
                # entries that built it).
                cidx, centry = conf
                if self.membership.apply(g, cidx, centry) is not None:
                    self._patch_group_config(g)
            if self._local[g]:
                # Our uncommitted leader-era proposals may or may not be
                # inside the installed state; requeue them all — the
                # transferred dedup window skips any that were, and the
                # rest get their honest retry.
                with self._prop_lock:
                    self._props[g].extendleft(
                        reversed([d for (_, d) in self._local[g]]))
                    self._prop_len[g] += len(self._local[g])
                    self._fwd_groups.add(g)
                self._local[g] = []
            log.info("node %d g%d: installed snapshot at idx %d",
                     self.node_id, g, rec.last_idx)

    def _build_inbox(self):
        """Drain staging into ONE packed [G, P, IB_NCOLS+E] device array
        (core/step.py unpack_inbox).  Clean steps (nothing staged since
        the last build) reuse the prebuilt all-zero device buffer — the
        inbox is never donated, so the same buffers serve every clean
        step and the build costs nothing."""
        cfg = self.cfg
        E = cfg.max_entries_per_msg
        C = self._COL
        with self._stage_lock:
            clean = not self._stage_dirty
        if clean:
            if self._zero_inbox is None:
                G, P = cfg.num_groups, self.num_nodes
                self._zero_inbox = jnp.zeros((G, P, IB_NCOLS + E),
                                             jnp.int32)
            self._tick_seq = self._zero_seq
            return self._zero_inbox, {}
        with self._stage_lock:
            self._stage_dirty = False
            votes, apps = self._stage_votes, self._stage_apps
            app_arr = self._stage_app_arr
            self._stage_votes, self._stage_apps = {}, {}
            self._stage_app_arr = {}
            # The packed columnar staging buffer becomes the inbox base
            # (no copy — a fresh buffer replaces it for the next window);
            # the record dicts overlay it below.  Ownership transfers
            # here: after this drain only this thread touches `stg`, so
            # the single jnp.asarray below can never race a concurrent
            # _deliver scatter.  Columnar appends are always n == 0.
            stg = self._stg
            seq_arr = self._stg_a_seq
            col_arr = self._stg_a_arr
            self._stg = self._fresh_stage_cols()
            self._stg_a_seq = np.zeros_like(seq_arr)
            self._stg_a_arr = np.zeros_like(col_arr)
        for (g, s), v in votes.items():
            stg[g, s, C["v_type"]] = v.type
            stg[g, s, C["v_term"]] = v.term
            stg[g, s, C["v_last_idx"]] = v.last_idx
            stg[g, s, C["v_last_term"]] = v.last_term
            stg[g, s, C["v_granted"]] = v.granted
        stale: List[Tuple[int, int]] = []
        for (g, s), a in apps.items():
            if app_arr.get((g, s), 0) < col_arr[g, s]:
                # A columnar message for this slot arrived AFTER the
                # record was staged: the newer arrival wins, whatever its
                # form.  (An older record REQ displacing a newer columnar
                # response would also mis-bind the seq echo below.)
                stale.append((g, s))
                continue
            stg[g, s, C["a_type"]] = a.type
            stg[g, s, C["a_term"]] = a.term
            stg[g, s, C["a_prev_idx"]] = a.prev_idx
            stg[g, s, C["a_prev_term"]] = a.prev_term
            stg[g, s, C["a_n"]] = a.n
            stg[g, s, C["a_commit"]] = a.commit
            stg[g, s, C["a_success"]] = a.success
            stg[g, s, C["a_match"]] = a.match
            stg[g, s, IB_NCOLS:IB_NCOLS + a.n] = a.ent_terms[:a.n]
            if a.type == MSG_REQ:
                # Bind the seq echo to the request the device will
                # actually process (the record overlays the columnar
                # base, so its seq must overlay too).
                seq_arr[g, s] = a.seq
        for k in stale:
            del apps[k]
        self._tick_seq = seq_arr
        return jnp.asarray(stg), apps

    def _wal_phase(self, info) -> None:
        """Persist this tick's appends + hard-state changes, one fsync.

        Vectorized over groups: numpy masks pick out only the groups that
        did something this tick (leader append, accepted follower append,
        hard-state delta), so an idle group costs zero Python work — the
        round-1/2 hot loop was O(G) every tick regardless of activity.
        Entry records accumulate across all groups into ONE batched WAL
        call of uniform-term RANGE runs (type-5 records, the same ~4x
        framing cut the fused tick measured — storage/wal.py module
        doc), framed without a per-record Python round trip on the C++
        fast path (native/wal.cc)."""
        term = np.asarray(info.term)
        noop = np.asarray(info.noop)
        prop_acc = np.asarray(info.prop_accepted)
        app_from = np.asarray(info.app_from)
        mm = self.membership
        w_rg: List[int] = []         # RANGE runs: group, start, count,
        w_rs: List[int] = []         # term — plus the flat per-entry
        w_rc: List[int] = []         # payload list in run order.
        w_rt: List[int] = []
        w_data: List[bytes] = []

        def put_run(g: int, start: int, count: int, t: int) -> None:
            w_rg.append(g)
            w_rs.append(start)
            w_rc.append(count)
            w_rt.append(t)

        active = np.nonzero(noop | (prop_acc > 0) | (app_from >= 0))[0]
        # ONE lock hold pops every group's accepted proposals (a per-group
        # acquire inside the loop was ~256 lock round trips per saturated
        # tick at the G=10k/256-active bench shape).
        acc = np.nonzero(prop_acc > 0)[0]
        popped: Dict[int, List[bytes]] = {}
        if acc.size:
            with self._prop_lock:
                for g in acc.tolist():
                    n = int(prop_acc[g])
                    q = self._props[g]
                    popped[g] = [q.popleft() for _ in range(n)]
                    self._prop_len[g] -= n
        for g in active.tolist():
            n_acc = int(prop_acc[g])
            if noop[g] or n_acc:
                base = int(info.prop_base[g])
                t_g = int(term[g])
                if noop[g]:
                    put_run(g, base, 1, t_g)
                    w_data.append(b"")
                    self.payload_log.put(g, base, [b""], [t_g])
                if n_acc:
                    batch = popped[g]
                    # One uniform-term run for the whole accepted batch
                    # (leader appends share the leader's term).
                    put_run(g, base + 1, n_acc, t_g)
                    w_data.extend(batch)
                    self._local[g].extend(
                        zip(range(base + 1, base + 1 + n_acc), batch))
                    self.payload_log.put(g, base + 1, batch,
                                         [t_g] * n_acc)
                    if mm is not None:
                        # Conf entries entering the log as LEADER
                        # appends: index them for apply-at-commit (one
                        # leading-byte test per accepted proposal).
                        for off, d in enumerate(batch):
                            if d[:1] == _CONF_PREFIX and is_conf_entry(d):
                                mm.note_appended(g, base + 1 + off, d)
                    if self.tracer is not None:
                        # Bind spans to their log indexes (envelope
                        # stripped — spans are keyed by plain content).
                        self.tracer.note_append(
                            g, base + 1,
                            [unwrap(p)[1].decode("utf-8", "replace")
                             for p in batch])
                self.metrics.proposals += n_acc
            src = int(app_from[g])
            if src >= 0:
                rec = self._tick_apps.get((g, src))
                if rec is None:      # staged slot raced away; next resend
                    continue         # re-delivers — raft tolerates loss
                start = int(info.app_start[g])
                new_len = int(info.new_log_len[g])
                n_app = int(info.app_n[g])
                for (rs, rc, rt) in split_uniform_runs(
                        start, rec.ent_terms[:n_app]):
                    put_run(g, rs, rc, rt)
                w_data.extend(rec.payloads[:n_app])
                if self.witness_self and n_app:
                    self.metrics.witness_appends += n_app
                self.payload_log.put(g, start, rec.payloads,
                                     rec.ent_terms, new_len=new_len)
                if mm is not None:
                    if info.app_conflict[g]:
                        # Clobbered suffix: conf entries in it never
                        # commit here.
                        mm.note_truncated(g, start)
                    # Conf entries entering as FOLLOWER appends (normal
                    # replication or host catch-up).
                    for off, d in enumerate(rec.payloads[:n_app]):
                        if d[:1] == _CONF_PREFIX and is_conf_entry(d):
                            mm.note_appended(g, start + off, d)
                if info.app_conflict[g] and self._local[g]:
                    # The new leader's suffix clobbered entries we
                    # appended as a (now deposed) leader: requeue their
                    # payloads for a fresh propose/forward round.
                    mine = self._local[g]
                    requeue = [d for (ix, d) in mine if ix >= start]
                    if requeue:
                        with self._prop_lock:
                            self._props[g].extendleft(reversed(requeue))
                            self._prop_len[g] += len(requeue)
                            self._fwd_groups.add(g)
                    self._local[g] = [(ix, d) for (ix, d) in mine
                                      if ix < start]
                if info.app_conflict[g] and self._applied[g] >= start:
                    # Should be unreachable since replay stopped
                    # publishing the uncommitted tail (committed entries
                    # never conflict-truncate); kept as a loud guard —
                    # the reference applies at append and has exactly
                    # this hazard (SURVEY.md §3.2 quirk).
                    log.warning("node %d g%d: conflict truncation below "
                                "applied=%d; state machine may have seen "
                                "an uncommitted entry", self.node_id, g,
                                self._applied[g])
                    self._applied[g] = min(self._applied[g], start - 1)
        # Hard-state delta detection is one vectorized compare over [G, 3].
        hs = np.stack([term, np.asarray(info.voted_for),
                       np.asarray(info.commit)], axis=1)
        hard_changed = np.nonzero((hs != self._hard_np).any(axis=1))[0]
        # Entries land before hard states (etcd wal.Save order): a torn
        # tail can then never leave a hard state referencing lost entries.
        if w_rg:
            self.wal.append_ranges(w_rg, w_rs, w_rc, w_rt, w_data)
        if hard_changed.size:
            self.wal.set_hardstates(hard_changed, hs[hard_changed, 0],
                                    hs[hard_changed, 1],
                                    hs[hard_changed, 2])
            self._hard_np[hard_changed] = hs[hard_changed]
        self.wal.sync()

    def _build_catchups(self, info) -> Dict[Tuple[int, int], AppendRec]:
        """Host-built AppendEntries for followers beyond the device ring.

        The device term ring only describes the last W log positions; a
        follower whose next_idx has fallen out of that window gets empty
        heartbeats from the device (core/step.py Phase 9 window guard).
        The leader HOST owns the full (term, payload) history
        (storage/log.py), so it constructs the out-of-window appends here
        — the analog of etcd MemoryStorage-backed sendAppend for entries
        the in-memory window no longer covers.  Responses flow back
        through the normal device path, advancing next_idx/match until
        the follower re-enters the window.
        """
        cfg = self.cfg
        W, E = cfg.log_window, cfg.max_entries_per_msg
        self._snap_due = []
        role = np.asarray(info.role)
        if not (role == LEADER).any():
            return {}
        next_idx = np.asarray(info.next_idx)            # [G, P]
        log_len = np.asarray(info.new_log_len)          # [G]
        commit = np.asarray(info.commit)
        term = np.asarray(info.term)
        # Margin of 2E: start host catch-up slightly before the hard edge
        # of the ring so a race with concurrent appends cannot strand the
        # follower on garbage ring reads.  The transition-table floor is
        # a second, independent send-suppression edge (core/step.py
        # in_window requires min_acc >= floor): more than K term
        # transitions in the window raise it above the ring edge, and a
        # follower below it would otherwise only ever see empty
        # heartbeats.  Its lag test is the exact complement of the
        # device guard (min_acc = max(next_idx-1, 1) for a non-empty
        # send), needs no race margin — info.floor IS the floor this
        # tick's sends were gated on — and is gated on the follower
        # actually having entries to fetch, which keeps healthy
        # followers out of the scan.
        floor = np.asarray(info.floor)                  # [G]
        lag = (role == LEADER)[:, None] & (next_idx >= 1) \
            & ((next_idx - 1 <= log_len[:, None] - W + 2 * E)
               | ((next_idx <= log_len[:, None])
                  & (np.maximum(next_idx - 1, 1) < floor[:, None])))
        lag[:, self.self_id] = False
        # Prune pacing state for peers that caught back up (its purpose
        # is served) and stale snapshot cooldowns (any in-flight transfer
        # resolves within a few cooldowns) — both maps are bounded at
        # O(G*P) but would otherwise hold dead entries forever.
        if self._catchup_sent:
            for k in [k for k in self._catchup_sent if not lag[k]]:
                del self._catchup_sent[k]
        if self._snap_sent:
            horizon = self._tick_no - 128 * self.cfg.election_ticks
            for k in [k for k, t in self._snap_sent.items()
                      if t < horizon]:
                del self._snap_sent[k]
        out: Dict[Tuple[int, int], AppendRec] = {}
        for g, d in zip(*np.nonzero(lag)):
            g, d = int(g), int(d)
            ni = int(next_idx[g, d])
            prev_sent = self._catchup_sent.get((g, d))
            if prev_sent is not None and prev_sent[0] == ni \
                    and self._tick_no - prev_sent[1] < 4:
                continue        # no progress yet; give the ack time
            avail = self.payload_log.length(g)
            n = min(E, avail - ni + 1)
            got = self.payload_log.try_tail_with_terms(g, ni, n) \
                if n > 0 else None
            if got is None:
                if ni <= self.payload_log.start(g):
                    # Beyond the compacted prefix: needs a full state
                    # transfer (InstallSnapshot), queued by _send_phase.
                    self._snap_due.append((g, d, int(term[g])))
                continue
            prev_term, ents = got
            self._catchup_sent[(g, d)] = (ni, self._tick_no)
            if self.tracer is not None and ents:
                self.tracer.note_replicate(g, ni - 1 + len(ents))
            out[(g, d)] = AppendRec(
                group=g, type=MSG_REQ, term=int(term[g]),
                prev_idx=ni - 1, prev_term=prev_term,
                ent_terms=[t for (t, _) in ents],
                payloads=[p for (_, p) in ents],
                commit=min(int(commit[g]), ni - 1 + len(ents)),
                seq=self._tick_no)
            self.metrics.catchup_appends += 1
        return out

    def _send_phase(self, outbox, info) -> None:
        cfg = self.cfg
        batches: Dict[int, TickBatch] = {}

        def batch_for(dst0: int) -> TickBatch:
            return batches.setdefault(dst0, TickBatch())

        catchups = self._build_catchups(info)

        # Columnar emission (transport/base.py ColRecs): votes and
        # payload-free appends (heartbeats + all responses) ship as
        # fancy-indexed numpy column arrays — zero per-message Python.
        # Only payload-carrying appends (count ∝ real replication
        # traffic) and catch-up substitutions take the record path.
        vg, vd = np.nonzero(outbox.v_type)
        if vg.size:
            v_cols = {f: np.ascontiguousarray(
                getattr(outbox, "v_" + f)[vg, vd], dtype=np.int32)
                for f in ("type", "term", "last_idx", "last_term",
                          "granted")}
            for d in np.unique(vd).tolist():
                rows = vd == d
                b = batch_for(d)
                if b.cols is None:
                    b.cols = ColRecs()
                b.cols.v_group = np.ascontiguousarray(vg[rows],
                                                      dtype=np.int32)
                for f, col in v_cols.items():
                    setattr(b.cols, "v_" + f, col[rows])

        ag, ad = np.nonzero(outbox.a_type)
        emitted = set()
        if ag.size:
            a_type_r = np.asarray(outbox.a_type[ag, ad])
            a_n_r = np.asarray(outbox.a_n[ag, ad])
            # Record path: REQs that carry entries, or whose slot has a
            # pending host catch-up to substitute.
            is_req = a_type_r == MSG_REQ
            rec_rows = is_req & (a_n_r > 0)
            if catchups:
                cu_mask = np.zeros((cfg.num_groups, self.num_nodes), bool)
                for (g, d) in catchups:
                    cu_mask[g, d] = True
                rec_rows |= is_req & cu_mask[ag, ad]
            col_rows = ~rec_rows
            if col_rows.any():
                # seq: REQs carry this tick's number; responses echo the
                # seq of the staged request they answer (ReadIndex round
                # binding, same contract as the record path).
                seq_all = np.where(is_req, np.int64(self._tick_no),
                                   self._tick_seq[ag, ad])
                a_cols = {f: np.ascontiguousarray(
                    getattr(outbox, "a_" + f)[ag, ad], dtype=np.int32)
                    for f in ("type", "term", "prev_idx", "prev_term",
                              "commit", "success", "match")}
                for d in np.unique(ad[col_rows]).tolist():
                    rows = col_rows & (ad == d)
                    b = batch_for(d)
                    if b.cols is None:
                        b.cols = ColRecs()
                    b.cols.a_group = np.ascontiguousarray(
                        ag[rows], dtype=np.int32)
                    for f, col in a_cols.items():
                        setattr(b.cols, "a_" + f, col[rows])
                    b.cols.a_seq = np.ascontiguousarray(
                        seq_all[rows], dtype=np.int64)
            ridx = np.nonzero(rec_rows)[0]
            rg, rd = ag[ridx], ad[ridx]
            a_ents_rows = np.asarray(outbox.a_ents[rg, rd]) \
                if ridx.size else None
            for i, (g, d, tm, prev, pt, n, cm) in enumerate(
                    zip(rg.tolist(), rd.tolist(),
                        np.asarray(outbox.a_term[rg, rd]).tolist(),
                        np.asarray(outbox.a_prev_idx[rg, rd]).tolist(),
                        np.asarray(outbox.a_prev_term[rg, rd]).tolist(),
                        a_n_r[ridx].tolist(),
                        np.asarray(outbox.a_commit[rg, rd]).tolist())):
                cu = catchups.pop((g, d), None)
                if cu is not None:
                    # The device could only offer an empty heartbeat to
                    # this out-of-window follower; substitute the
                    # host-built catch-up append (same slot, newest-wins
                    # semantics).
                    batch_for(d).appends.append(cu)
                    continue
                # The device ring can reference positions below the
                # payload floor (log-length regression after conflict
                # truncation / snapshot install, or a concurrent
                # compaction advancing the floor).  try_slice is
                # atomic against the compactor; on miss, drop the
                # message — the peer is served by catch-up or
                # snapshot on a later tick.
                payloads = self.payload_log.try_slice(g, prev + 1, n)
                if payloads is None:
                    continue
                if self.tracer is not None and n:
                    # Replicate stamp: the entries left for a follower
                    # (first transmission wins per index).
                    self.tracer.note_replicate(g, prev + n)
                batch_for(d).appends.append(AppendRec(
                    group=g, type=MSG_REQ, term=tm,
                    prev_idx=prev, prev_term=pt,
                    ent_terms=a_ents_rows[i, :n].tolist(),
                    payloads=payloads, commit=cm,
                    seq=self._tick_no))
            if catchups:
                emitted_mask = np.zeros(
                    (cfg.num_groups, self.num_nodes), bool)
                emitted_mask[ag, ad] = True
                emitted = {k for k in catchups if emitted_mask[k]}
        for (g, d), cu in catchups.items():
            if (g, d) in emitted:
                # The device emitted a (response) message for this slot;
                # the receiver stages one append per (group, src), newest
                # wins — don't clobber it.  Un-record the pacing entry so
                # the catch-up is rebuilt next tick, not in 4.
                self._catchup_sent.pop((g, d), None)
                continue
            batch_for(d).appends.append(cu)

        # InstallSnapshot dispatch (rate-limited: transfers are bulky and
        # idempotent, a cooldown per (group, peer) is plenty).
        if self._snap_due and self.snapshot_provider is not None:
            cooldown = 8 * cfg.election_ticks
            for g, d, term_g in self._snap_due:
                last = self._snap_sent.get((g, d), -cooldown)
                if self._tick_no - last < cooldown:
                    continue
                got = self.snapshot_provider(g)
                if got is None:
                    continue
                last_idx, blob = got
                if last_idx <= self.payload_log.start(g) \
                        and last_idx < self.payload_log.length(g):
                    # The snapshot doesn't reach the floor the follower
                    # needs (applier lagging behind compaction — cannot
                    # happen through the RaftDB path, which compacts only
                    # below its own applied index); don't send garbage.
                    continue
                self._snap_sent[(g, d)] = self._tick_no
                # Ship the dedup window AS OF the snapshot's applied
                # index inside the blob: without it the receiver either
                # re-applies a forward-retried duplicate the snapshot
                # already contains, or (shipping the live window) skips
                # entries its installed state lacks — both diverge.
                blob = wrap_snapshot(
                    self._dedup[g].pairs_upto(last_idx), blob)
                mm = self.membership
                if mm is not None and not mm.is_default(g):
                    # The transfer skips the log: ship the active
                    # config so the receiver cannot keep a voter set
                    # from before the skipped conf entries.
                    c = mm.config(g)
                    blob = wrap_snapshot_conf(
                        c.index, c.entry(0), blob)
                batch_for(d).snapshots.append(SnapshotRec(
                    group=g, last_idx=last_idx,
                    last_term=self.payload_log.term_of(g, last_idx),
                    term=term_g, blob=blob))
                # Resume replication above the transfer; see
                # set_peer_progress for why this is safe if it is lost.
                self.state = set_peer_progress(
                    self.state, g, d, last_idx + 1)
                self.metrics.snapshots_sent += 1
        self._snap_due = []

        # Proposal forwarding: anything still queued while we are not the
        # leader goes to the leader hint, and is tracked for retry until
        # its commit is observed (see _fwd above).  Deadlines are in
        # LEASE-CLOCK (timer) units, not tick numbers: the event-driven
        # loop elides idle steps, so "4 * election_ticks" tick numbers
        # could be many times that in wall time — a proposal forwarded
        # to a leader that died the same instant then sat unreclaimed
        # for tens of seconds while the client's retries all timed out
        # (found by the process-plane read nemesis: the while-down PUT
        # stall).  Timer units track wall time by construction.
        role = info.role
        hint = info.leader_hint
        clock = self._lease_clock
        deadline = clock + 4 * cfg.election_ticks
        with self._prop_lock:
            # O(dirty), not O(G): only groups with queued or in-flight
            # forwarded proposals are walked — at G=10k the full-range
            # walk was most of this phase's Python even with every
            # queue empty.
            for g in list(self._fwd_groups):
                fwd_g = self._fwd[g]
                if fwd_g and role[g] == LEADER:
                    # WE became the leader: an in-flight forward
                    # targeted a PREVIOUS leader and nobody else will
                    # commit it — reclaim everything immediately (the
                    # envelope dedup collapses any copy that did land,
                    # so the requeue is always safe).  Without this,
                    # a proposal forwarded to a leader that crashed
                    # before our own election sat in limbo until the
                    # deadline even though we could accept it NOW.
                    self._props[g].extendleft(
                        reversed([p for (p, _) in fwd_g]))
                    self._prop_len[g] += len(fwd_g)
                    self._fwd[g] = []
                    fwd_g = self._fwd[g]
                if fwd_g:
                    expired = [p for (p, d) in fwd_g if d <= clock]
                    if expired:
                        self._fwd[g] = [(p, d) for (p, d) in fwd_g
                                        if d > clock]
                        self._props[g].extendleft(reversed(expired))
                        self._prop_len[g] += len(expired)
                h = int(hint[g])
                if role[g] != LEADER and h >= 0 and h != self.self_id \
                        and self._props[g]:
                    fwd = list(self._props[g])
                    self._props[g].clear()
                    self._prop_len[g] = 0
                    for p in fwd:
                        batch_for(h).proposals.append(
                            ProposalRec(group=g, payload=p))
                        self._fwd[g].append((p, deadline))
                elif not self._props[g] and not self._fwd[g]:
                    self._fwd_groups.discard(g)

        for dst0, batch in batches.items():
            self.transport.send(dst0 + 1, batch)
            self.metrics.msgs_sent += (len(batch.votes)
                                       + len(batch.appends)
                                       + len(batch.proposals)
                                       + len(batch.snapshots))
            if batch.cols is not None:
                self.metrics.msgs_sent += (batch.cols.n_votes()
                                           + batch.cols.n_appends())

    def _publish_phase(self, info) -> None:
        # Vectorized group selection: only groups whose commit advanced
        # past their applied point do any Python work this tick.
        commit = np.asarray(info.commit)
        ready = np.nonzero(commit > self._applied)[0]
        for g in ready.tolist():
            c = int(commit[g])
            a = int(self._applied[g])
            if self.tracer is not None:
                self.tracer.note_commit(g, c)
            fwd = self._fwd[g]
            # One locked read for the whole newly-committed range — a
            # per-entry get() pays a lock acquisition per entry, which
            # dominated this phase at high commit rates.
            datas = self.payload_log.slice(g, a + 1, c - a)
            # Loud, not silent (and not a stripable assert): a short read
            # here means the host payload log diverged from the device
            # commit (a sync bug) — skipping the missing committed
            # entries would silently fork this replica's state machine.
            if len(datas) != c - a:
                raise RuntimeError(
                    f"g{g}: payload log shorter than commit "
                    f"({a}+{len(datas)} < {c})")
            if fwd:
                # Forwarded proposal observed committed: retire it
                # (exact match — envelope ids are unique).  Tick-thread
                # only (_fwd has no lock); almost always empty — only
                # follower-routed proposals enter it.
                for data in datas:
                    for k, (p, _) in enumerate(fwd):
                        if p == data:
                            del fwd[k]
                            break
            mm = self.membership
            if mm is not None and mm.has_appended(g):
                # Conf entries committing in this range: APPLY (device
                # masks + WAL baseline) and SCRUB them from the SQL
                # apply stream — the state machine sees an empty entry
                # where the conf change sat (raft.go:84-87 parity).
                # Index-driven: zero per-entry work on the hot path.
                for idx, _noted in mm.take_committed(g, a, c):
                    d = datas[idx - a - 1]
                    if not is_conf_entry(d):
                        continue          # stale note (overwritten slot)
                    if mm.apply(g, idx, d) is not None:
                        self._patch_group_config(g)
                    datas[idx - a - 1] = b""
            if any(datas):
                # RAW batch, one queue put per group per tick: the
                # per-entry unwrap/dedup/utf-8 chain (~2.5 µs each, the
                # bulk of this phase at saturation) now runs on the
                # CONSUMER thread (runtime/db.py _expand_commit_item),
                # off the tick's critical path.  All-empty ranges
                # (no-op/conf entries) publish nothing, as before.
                self.commit_q.put((RAW_BATCH, g, a, datas))
            self._applied[g] = c
            self.metrics.commits += c - a
            if self._local[g]:
                # Committed own-proposals need no deposal-requeue cover.
                self._local[g] = [(ix, d) for (ix, d) in self._local[g]
                                  if ix > c]
