"""FusedClusterNode — the durable co-located deployment.

The distributed runtime (runtime/node.py) runs one RaftNode per process
and pays one device dispatch per peer per tick; through a remote-TPU
tunnel each dispatch costs tens of milliseconds, so a P-peer cluster is
dispatch-bound long before consensus math matters.  When all P peers of
every group are co-located on ONE chip — the reference's Procfile
cluster collapsed into a single host process — the TPU-first shape is
the fused cluster step (core/cluster.py): all P peers × G groups advance
in one compiled program, messages delivered by an on-device transpose,
and the host crosses the boundary once per tick with a packed StepInfo.

Durability keeps the reference's per-batch contract (reference
raft.go:227-235: wal.Save → transport.Send → publish) with the dispatch
itself as the send barrier:

  messages composed at tick t are OBSERVED by their receivers only
  inside step t+1 — and the host does not dispatch step t+1 until every
  peer's tick-t appends and hard states are fsynced.

So a follower's success response (composed at t, seen by the leader at
t+1) never reaches the leader before the entries it acknowledges are
durable on the follower — exactly the raft requirement the reference
gets from saving before sending.  Publish (commit delivery to the apply
layer) happens after the same tick's save, before the next dispatch.

The durable host phase itself — propose queues, WAL + payload-log
writes, the fsync barrier, publish, membership apply-at-commit — lives
in runtime/hostplane.py (ClusterHostPlane), SHARED with the multi-chip
mesh runtime (runtime/mesh.py MeshClusterNode): the two runtimes differ
only in how `_device_step` dispatches the consensus math.

Scope (documented, not hidden): this runtime targets the co-located
steady state.  Followers that fall behind the device ring window are
served by the distributed runtime's host catch-up / InstallSnapshot
machinery, not here — a fused-mode follower outside the window waits
for the window to come back around (bounded lag under steady load).
Crash recovery is full per-peer WAL replay (reference raft.go:122-134).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

import jax.numpy as jnp

from raftsql_tpu.core.cluster import (cluster_multistep_host,
                                      cluster_step_host)
# Re-exported for existing import sites (tests, tools): the host plane
# moved to runtime/hostplane.py in the mesh-runtime split.
from raftsql_tpu.runtime.hostplane import (_C,  # noqa: F401
                                           _expand_ranges,
                                           _read_committed_epoch,
                                           ClusterHostPlane)

__all__ = ["FusedClusterNode", "FusedPipe", "ClusterHostPlane",
           "_C", "_expand_ranges", "_read_committed_epoch",
           "MeshClusterNode"]


class FusedClusterNode(ClusterHostPlane):
    """The single-device durable runtime: ClusterHostPlane with the
    fused cluster step (core/cluster.py) as its device program —
    including the multi-step dispatch (RAFTSQL_FUSED_STEPS) and the
    device busy bit that drives idle parking."""

    # Steady-state [P] i32 lockstep advance, built once: the None and
    # the skew branch must ship the SAME dtype/shape to the jitted step
    # or a mid-run skew schedule retraces it (and the recompile pause
    # can depose a healthy leader — the jit-stability invariant).
    _ti_ones = None

    def _device_step(self, prop_n: np.ndarray,
                     timer_inc: Optional[np.ndarray] = None):
        """Dispatch one cluster step; returns (packed-info device array,
        device busy bit).  `timer_inc` is the per-peer [P] timer advance
        (None = lockstep 1s, the steady-state fast path)."""
        if self._ti_ones is None:
            self._ti_ones = jnp.ones((self.cfg.num_peers,), jnp.int32)
        ti = self._ti_ones if timer_inc is None \
            else jnp.asarray(np.asarray(timer_inc, np.int32))
        if self._steps > 1:
            self.states, self.inboxes, pinfos_dev, busy = \
                cluster_multistep_host(self.cfg, self.states,
                                       self.inboxes, self._steps,
                                       jnp.asarray(prop_n), ti)
            return pinfos_dev, busy
        self.states, self.inboxes, pinfo_dev, busy = cluster_step_host(
            self.cfg, self.states, self.inboxes, jnp.asarray(prop_n), ti)
        return pinfo_dev, busy


class FusedPipe:
    """The propose/commit/error facade (reference raftpipe.go:3-17) over
    a ClusterHostPlane runtime (fused or mesh), so the whole SQL stack
    above consensus — RaftDB ack routing, HTTP API, CLI — serves from
    the co-located runtime unchanged.  Peer 0's commit stream is the
    apply plane: one process IS the cluster, so one local replica
    applies (the other peers' durability lives in their WALs; a restart
    replays any of them)."""

    def __init__(self, node: ClusterHostPlane):
        self.node = node
        # This facade is the only consumer and it reads peer 0's
        # stream; skip materializing the other peers' publishes.
        node.publish_peers = {0}
        self.commit_q = node.commit_q(0)

    def propose(self, group: int, payload: bytes,
                pid: Optional[int] = None,
                deadline_step: Optional[int] = None) -> None:
        # `pid` (client retry token) is accepted for facade parity and
        # dropped: fused proposals are routed on the host and never
        # forward-retried, so payloads travel PLAIN (no envelope to
        # carry the token — see runtime/db.py RAW_PLAIN contract).
        # `deadline_step` (overload plane, device-step units) rides to
        # the hostplane so expired work is shed before staging.
        self.node.propose_many(group, [payload],
                               deadline_step=deadline_step)

    @property
    def error(self) -> Optional[Exception]:
        return self.node.error

    def close(self) -> Optional[Exception]:
        self.node.stop()
        return self.node.error


def __getattr__(name):
    # Back-compat: MeshClusterNode lived here before the mesh runtime
    # became its own subsystem (runtime/mesh.py).  Lazy to avoid a
    # module cycle (mesh.py imports FusedPipe from this module).
    if name == "MeshClusterNode":
        from raftsql_tpu.runtime.mesh import MeshClusterNode
        return MeshClusterNode
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
