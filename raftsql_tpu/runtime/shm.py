"""Shared-memory snapshot plane: worker-mapped read fast path (PR 12).

The `--workers N` deployment (runtime/ring.py) moved HTTP parsing out
of the engine process, but every GET still paid a full mmap-ring round
trip INTO the engine: REQ slot, engine-side read pool, CPL slot.  The
PR 9 reads ladder put that cost at the top of the read profile — even
a `local` read, which touches no consensus state at all, crossed the
ring twice.

This module removes the round trip for the four read modes whose
freshness evidence is DATA, not a quorum round: the engine publishes
each group's applied SQL delta stream plus the `[G]` commit-watermark,
leader and lease columns into one mmap'd file in the ring directory;
workers map it READ-ONLY, feed per-group in-memory SQLite replicas
from the delta log, and serve

  * `local`    — replica catch-up to the published applied index;
  * `session`  — only once the published applied index covers the
    client's X-Raft-Session watermark (else fall back to the ring,
    where the engine blocks authoritatively);
  * `follower` — only once published applied covers published commit;
  * `linear`   — only while the published lease deadline (stamped by
    the engine from the SAME `now + max_clock_skew` bound its own
    lease reads enforce, runtime/node.py lease_deadline_s) covers the
    worker's CLOCK_MONOTONIC now (system-wide on Linux, so the
    deadline transfers across processes verbatim)

entirely inside the worker process.  Anything not provable from the
mapping — stale publisher heartbeat, watermark not yet covered, lease
lapsed, log overflow, epoch mismatch — FAILS CLOSED to the ring path:
the fast path may only ever skip work, never weaken a mode's contract.

Concurrency design
------------------

One writer (the engine's apply thread + a refresh thread, serialized
by a lock), many reader processes.  The header + per-group table are
guarded by a SEQLOCK: the writer bumps `seq` to odd, writes, bumps to
even; a reader snapshots seq, copies, re-checks (retry on odd/changed).
The delta log is APPEND-ONLY and never rewritten below `log_head`, so
readers copy log bytes WITHOUT the seqlock — a torn table read retries
in microseconds, while log consumption can never livelock behind a
fast writer.  When the log fills, the writer sets the `log_full` flag
and stops publishing deltas; readers treat the region as permanently
dead and every read falls back (the engine keeps serving via the
ring).  A restarted engine draws a fresh random `epoch`: a worker
whose mapping no longer matches its attached epoch marks the plane
dead — remapping a new region mid-flight could alias a rolled-back
applied index, so restart recovery is deliberately NOT transparent
(ISSUE 12: stale-epoch remap must fail closed).

The memory-ordering assumption is declared machine-checked below
(`# raftlint: assumes=x86-tso`): raftlint's memory-model rule refuses
seqlock-annotated protocol code in any file that does not declare its
hardware store-order dependence.
"""
from __future__ import annotations

# raftlint: assumes=x86-tso -- the seqlock issues no explicit barriers:
# it relies on cross-process mmap stores becoming visible in program
# order, which x86-TSO guarantees (stores are not reordered with other
# stores, so the even-seq header rewrite publishes log_head only after
# the log/table bytes land).  On weakly-ordered architectures (ARM,
# POWER) a reader could observe the even seq before the data stores and
# take an undetected torn snapshot; this plane targets x86-64/Linux
# (the jax_graft host platform) and must grow fences or per-row
# checksums before being trusted elsewhere.

import mmap
import os
import secrets
import struct
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

# Header: magic, version, flags, num_groups, epoch, seq, log_head,
# log_cap, pub_ns, keymap_epoch.  64 bytes with padding to keep the
# group table aligned.  keymap_epoch (hdr[9]) is the elastic-keyspace
# mapping version (raftsql_tpu/reshard/): a worker serving shm reads
# under a routing table older than the publisher's FAILS CLOSED to the
# ring path until it refreshes its mapping.
_MAGIC = 0x534E4150                      # "SNAP"
_VERSION = 1
_FLAG_LOG_FULL = 1
_HDR = struct.Struct("<IHHIQQQQQQ")      # 60 bytes used
_HDR_SIZE = 64
# Per-group row: applied, commit, base_index, lease_deadline_ns,
# leader (1-based, 0 unknown), pad.
_ROW = struct.Struct("<QQQQIi")
_ROW_SIZE = _ROW.size                    # 40 bytes
# Log record header: length of payload, kind, group, index.
_REC = struct.Struct("<IBIQ")
KIND_DELTA = 1                           # payload = one SQL statement
KIND_BASE = 2                            # payload = serialized image

SHM_FILE = "snap.shm"
DEFAULT_BYTES = 32 << 20

# A mapping whose publisher heartbeat is older than this is treated as
# dead for LEASE reads only: local/session/follower freshness is
# proven by the watermarks themselves, but a lease deadline published
# by a wedged engine must not outlive the engine's own refresh cadence
# by much more than the lease horizon.
PUB_STALE_NS = 250_000_000


def shm_path(ring_dir: str) -> str:
    return os.path.join(ring_dir, SHM_FILE)


class ShmSnapshotPublisher:
    """Engine side: owns the mapping read-write, publishes base images,
    applied deltas and the watermark/lease/leader table.

    publish_deltas runs on the apply thread (runtime/db.py _apply_run,
    before acks fire — a worker can then always reach an acked PUT's
    watermark); refresh() runs on a short-interval thread owned by the
    RingServer and restamps commit/leader/lease columns + the
    publisher heartbeat."""

    def __init__(self, ring_dir: str, num_groups: int,
                 size: Optional[int] = None):
        size = size or int(os.environ.get("RAFTSQL_SHM_BYTES",
                                          DEFAULT_BYTES))
        self.num_groups = num_groups
        self._table_off = _HDR_SIZE
        self._log_off = _HDR_SIZE + num_groups * _ROW_SIZE
        size = max(size, self._log_off + (1 << 20))
        self.path = shm_path(ring_dir)
        # No O_TRUNC, grow-only ftruncate: re-creating the region over
        # a predecessor's path (engine restart with the old refresh
        # thread still live) must never let the file size dip — a
        # store through the old mapping while the file is momentarily
        # short of the mapped range is SIGBUS, not an exception.  Old
        # readers die on the epoch flip exactly as before; stale log
        # bytes past the new head are unreachable (head moves only
        # after its bytes are written).
        fd = os.open(self.path, os.O_CREAT | os.O_RDWR, 0o600)
        try:
            if os.fstat(fd).st_size < size:
                os.ftruncate(fd, size)
            self._mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        self._size = size
        self._lock = threading.Lock()
        self._seq = 0
        self._log_head = 0
        self._log_cap = size - self._log_off
        self._full = False
        self.epoch = secrets.randbits(63) | 1    # never 0
        self.keymap_epoch = 0      # elastic-keyspace mapping version
        self._rows = [[0, 0, 0, 0, 0] for _ in range(num_groups)]
        #             applied, commit, base_index, lease_ns, leader
        # Stream tee (replica/publisher.py): called under _lock with
        # ("deltas", per_g) / ("base", group, index, blob) /
        # ("keymap", epoch) the instant a record lands — and, unlike
        # the mmap log, UNCONDITIONALLY: log overflow kills the local
        # fast path (readers can't trust a truncated log) but the
        # stream stays live, because subscribers are re-imaged from
        # fresh KIND_BASE serializations, not from this log.  None
        # (the default) keeps the publisher byte-for-byte inert.
        self.tee: Optional[Callable] = None
        self._serialize_of: Optional[Callable] = None
        # Deltas arriving before start() buffer here: the log must
        # open with each group's base image so a replica can never
        # replay a delta stream whose prefix it is missing.
        self._pending: Optional[List[Dict[int, list]]] = []
        self._write_header(pub_ns=time.monotonic_ns())
        self._write_table()

    # -- writer internals (callers hold _lock) --------------------------

    def _write_header(self, pub_ns: int) -> None:
        flags = _FLAG_LOG_FULL if self._full else 0
        self._mm[0:_HDR.size] = _HDR.pack(
            _MAGIC, _VERSION, flags, self.num_groups, self.epoch,
            self._seq, self._log_head, self._log_cap, pub_ns,
            self.keymap_epoch)

    def _write_table(self) -> None:
        off = self._table_off
        for row in self._rows:
            self._mm[off:off + _ROW_SIZE] = _ROW.pack(
                row[0], row[1], row[2], row[3], row[4], 0)
            off += _ROW_SIZE

    def _publish_locked(self, writes: Callable[[], None]) -> None:  # raftlint: seqlock
        """Seqlock write protocol: odd → mutate → even.  The log bytes
        appended by `writes` land BEFORE the header's log_head moves —
        readers never see a head past initialized bytes."""
        self._seq += 1                       # odd: writer in critical
        self._write_header(pub_ns=time.monotonic_ns())
        writes()
        self._seq += 1                       # even: consistent again
        self._write_header(pub_ns=time.monotonic_ns())

    def _append_locked(self, kind: int, group: int, index: int,
                       payload: bytes) -> bool:
        need = _REC.size + len(payload)
        if self._log_head + need > self._log_cap:
            self._full = True
            return False
        off = self._log_off + self._log_head
        self._mm[off:off + _REC.size] = _REC.pack(
            len(payload), kind, group, index)
        self._mm[off + _REC.size:off + need] = payload
        self._log_head += need
        return True

    def _run_locked(self, per_g: Dict[int, list]) -> None:
        """Append one applied run's deltas (caller holds _lock, inside
        the seqlock critical section)."""
        for group, items in per_g.items():
            row = self._rows[group]
            for (sql, index) in items:
                if index <= row[0]:
                    continue                 # covered by base/duplicate
                if not self._append_locked(KIND_DELTA, group, index,
                                           sql.encode("utf-8")):
                    return
                row[0] = index

    # -- engine-facing API ----------------------------------------------

    def start(self, serialize_of, applied_of) -> None:
        """Open the log: one base image per group (serialize_of(g) →
        (index, blob) or None), then every delta run buffered since
        the publisher was attached.  The attach-then-start ordering
        makes the stream complete: an apply finishing before its
        group's serialize is inside the base; one finishing after is a
        buffered delta ABOVE it (flushed here, in arrival order,
        before direct appends begin).  A group that HAS applied state
        (applied_of(g) > 0) but cannot produce an image would leave
        replicas with a truncated stream — the whole plane fails
        closed (log_full) rather than serve wrong prefixes."""
        self._serialize_of = serialize_of    # retained for stream resyncs
        bases = {}
        for g in range(self.num_groups):
            got = serialize_of(g)
            if got is not None and got[0] > 0:
                bases[g] = got
            elif int(applied_of(g)) > 0:
                with self._lock:
                    self._full = True
                    self._pending = None
                    self._publish_locked(lambda: None)
                return
        with self._lock:
            def writes():
                for g, (idx, blob) in bases.items():
                    if self._append_locked(KIND_BASE, g, idx, blob):
                        row = self._rows[g]
                        row[0] = max(row[0], idx)
                        row[2] = idx
                for per_g in (self._pending or ()):
                    self._run_locked(per_g)
                self._write_table()
            self._publish_locked(writes)
            self._pending = None

    def publish_base(self, group: int, blob: bytes, index: int) -> None:
        """Publish a group's full serialized image (snapshot install).
        Readers install the base when it passes their replica's applied
        index and replay deltas above it."""
        with self._lock:
            self._tee_locked("base", group, index, blob)
            if self._full:
                return

            def writes():
                if self._append_locked(KIND_BASE, group, index, blob):
                    row = self._rows[group]
                    row[0] = max(row[0], index)
                    row[2] = index
                    self._write_table()
            self._publish_locked(writes)

    def publish_deltas(self, per_g: Dict[int, List[Tuple[str, int]]]
                       ) -> None:
        """Publish one applied run: per group, the (sql, index) items
        just handed to the state machine, in apply order."""
        with self._lock:
            if self._pending is not None:
                self._pending.append(per_g)
                return               # pre-start: flushed into the log
                #                      (below any tee attach) by start()
            self._tee_locked("deltas", per_g)
            if self._full:
                return
            def writes():
                self._run_locked(per_g)
                self._write_table()
            self._publish_locked(writes)

    def refresh(self, commit_of, leader_of, lease_deadline_s) -> None:
        """Restamp the watermark/leader/lease columns + heartbeat from
        the engine's host caches (RingServer refresh thread).  Lease
        deadlines convert monotonic seconds → ns; 0.0 stays 0 (no
        lease)."""
        with self._lock:
            for g in range(self.num_groups):
                row = self._rows[g]
                try:
                    row[1] = max(row[1], int(commit_of(g)))
                    row[4] = int(leader_of(g)) + 1
                    d = lease_deadline_s(g)
                    row[3] = int(d * 1e9) if d > 0 else 0
                except Exception:            # noqa: BLE001
                    row[3] = 0               # fail closed, keep going
            self._publish_locked(self._write_table)

    def set_keymap_epoch(self, epoch: int) -> None:
        """Publish a new elastic-keyspace mapping version (reshard
        plane router flip).  Workers attached at an older value fail
        their shm reads closed until they refresh the mapping."""
        with self._lock:
            self._tee_locked("keymap", int(epoch))
            self.keymap_epoch = int(epoch)
            self._publish_locked(lambda: None)

    # -- stream-tee surface (replica/publisher.py) ----------------------

    def _tee_locked(self, *event) -> None:
        """Mirror one publish event to the stream tee (caller holds
        _lock).  The tee implementation only does non-blocking bounded
        queue puts; any failure is the stream plane's problem — it must
        never stall or fail the apply thread."""
        if self.tee is None:
            return
        try:
            self.tee(*event)
        except Exception:  # noqa: BLE001 -- tee must never stall applies
            pass

    def stream_register(self, fn: Callable[[], None]) -> Tuple[int, bool]:
        """Run a subscriber-registration callback under the publisher
        lock and return (log_head, log_full) from the same critical
        section: every record at or below the returned head is readable
        via read_log_records, and every event after it reaches the
        just-registered tee queue — no gap, and any overlap is absorbed
        by the replicas' resume-mode `index <= applied` dedup."""
        with self._lock:
            fn()
            return self._log_head, self._full

    def read_log_records(self, pos: int, head: int
                         ) -> List[Tuple[int, int, int, bytes]]:
        """Decode log records in [pos, head) as (kind, group, index,
        payload).  Bytes below a head returned by stream_register are
        append-only immutable, so this takes no lock and may run
        concurrently with the writer (same argument as the reader's
        _catch_up)."""
        out = []
        while pos + _REC.size <= head:
            off = self._log_off + pos
            ln, kind, group, index = _REC.unpack(
                self._mm[off:off + _REC.size])
            if pos + _REC.size + ln > head:
                break
            payload = bytes(self._mm[off + _REC.size:
                                     off + _REC.size + ln])
            pos += _REC.size + ln
            out.append((kind, group, index, payload))
        return out

    def fresh_base(self, group: int) -> Optional[Tuple[int, bytes]]:
        """A fresh (index, blob) image of one group for stream RESYNCs
        (overflowed log / lapped subscriber queue).  Calls the engine
        serializer retained by start(); that takes the state machine's
        own lock, NOT the publisher lock — never call this while
        holding _lock."""
        fn = self._serialize_of
        if fn is None:
            return None
        try:
            got = fn(group)
        except Exception:  # noqa: BLE001 -- resync just stays pending
            return None
        return got if got is not None and got[0] > 0 else None

    def table_snapshot(self):
        """(epoch, keymap_epoch, log_full, rows) with rows per group
        (applied, commit, base_index, lease_deadline_ns, leader) — the
        stream server's TABLE heartbeat source."""
        with self._lock:
            return (self.epoch, self.keymap_epoch, self._full,
                    [tuple(r) for r in self._rows])

    def close(self) -> None:
        with self._lock:
            try:
                self._mm.close()
            except (BufferError, ValueError):
                pass

    # test/diagnostic surface
    @property
    def log_full(self) -> bool:
        return self._full


class _GroupReplica:
    """One group's in-process SQLite replica, fed from the delta log.
    resume=True gives the state machine's own `index <= applied` skip,
    so re-feeding an overlapping window is harmless."""

    def __init__(self, group: int):
        from raftsql_tpu.models.sqlite_sm import SQLiteStateMachine
        self.sm = SQLiteStateMachine(":memory:", resume=True)
        self.group = group
        self.consumed = 0        # log bytes already fed


class ShmSnapshotReader:
    """Worker side: maps the snapshot region read-only and serves
    reads from per-group replicas.  Every public method FAILS CLOSED —
    returns None — whenever the mapping cannot PROVE the mode's
    freshness contract; the caller (runtime/ring.py RingClient) then
    takes the ordinary ring round trip."""

    def __init__(self, ring_dir: str):
        self.path = shm_path(ring_dir)
        fd = os.open(self.path, os.O_RDONLY)
        try:
            self._mm = mmap.mmap(fd, 0, prot=mmap.PROT_READ)
        finally:
            os.close(fd)
        self._lock = threading.Lock()
        self._dead = False
        hdr = self._read_header_raw()
        if hdr is None or hdr[0] != _MAGIC or hdr[1] != _VERSION:
            self.close()         # don't leak the mapping on a failed
            #                      attach — the caller never sees us
            raise RuntimeError(f"{self.path}: bad snapshot header")
        self.epoch = hdr[4]
        self.num_groups = hdr[3]
        # Elastic-keyspace mapping version this worker routes by.
        # try_read fails closed while the publisher's header reports a
        # different value; note_keymap_epoch revalidates after the
        # worker refreshed its key->group mapping.
        self._kmap_epoch = hdr[9]
        self._table_off = _HDR_SIZE
        self._log_off = _HDR_SIZE + self.num_groups * _ROW_SIZE
        self._replicas: Dict[int, _GroupReplica] = {}

    # -- mapping access -------------------------------------------------

    def _read_header_raw(self):
        try:
            return _HDR.unpack(self._mm[0:_HDR.size])
        except (ValueError, struct.error):
            return None

    def _snapshot_table(self):  # raftlint: seqlock fail-closed
        """Seqlock read of header + group table: (header, rows) or
        None after bounded retries / on any fail-closed condition.
        The epoch check pins the attachment: a restarted engine's
        fresh region (new epoch) permanently kills this reader."""
        if self._dead:
            return None
        for _ in range(64):
            h1 = self._read_header_raw()
            if h1 is None:
                return None
            if h1[0] != _MAGIC or h1[1] != _VERSION \
                    or h1[4] != self.epoch:
                self._dead = True            # stale epoch: fail closed
                return None
            if h1[5] & 1:                    # writer mid-update
                time.sleep(0)
                continue
            raw = bytes(self._mm[self._table_off:self._log_off])
            h2 = self._read_header_raw()
            if h2 is None or h2[5] != h1[5] or h2[4] != self.epoch:
                time.sleep(0)
                continue                     # torn: retry
            rows = [_ROW.unpack_from(raw, i * _ROW_SIZE)
                    for i in range(self.num_groups)]
            return h1, rows
        return None

    # raftlint: fail-closed
    def _catch_up(self, rep: _GroupReplica, target: int,
                  log_head: int) -> bool:
        """Feed the replica from the append-only log until its applied
        index reaches `target`.  Log bytes below log_head are immutable
        — no seqlock needed here.  False when the log ran out before
        the target (publisher hasn't written it yet — fall back)."""
        g = rep.group
        while rep.sm.applied_index() < target:
            if rep.consumed + _REC.size > log_head:
                return False
            off = self._log_off + rep.consumed
            ln, kind, group, index = _REC.unpack(
                self._mm[off:off + _REC.size])
            if rep.consumed + _REC.size + ln > log_head:
                return False
            payload = bytes(self._mm[off + _REC.size:
                                     off + _REC.size + ln])
            rep.consumed += _REC.size + ln
            if group != g:
                continue
            if kind == KIND_BASE:
                if index > rep.sm.applied_index():
                    rep.sm.install(payload, index)
            elif kind == KIND_DELTA:
                # resume-mode state machine skips index <= applied.
                rep.sm.apply(payload.decode("utf-8"), index)
        return True

    # -- read API --------------------------------------------------------

    # raftlint: fail-closed
    def try_read(self, mode: str, group: int, query: str,
                 watermark: int = 0
                 ) -> Optional[Tuple[str, int]]:
        """Serve one read entirely from the mapping: (rows, session
        watermark echo) — or None to fall back to the ring.  `mode` is
        local/session/follower/linear with the contracts documented in
        the module docstring."""
        from raftsql_tpu.models.sqlite_sm import is_select
        if not is_select(query):
            return None          # engine's 400 class — and NEVER let a
            #                      write mutate the worker-side replica
        snap = self._snapshot_table()
        if snap is None:
            return None
        hdr, rows = snap
        if hdr[2] & _FLAG_LOG_FULL:
            self._dead = True                # overflow: permanently out
            return None
        if hdr[9] != self._kmap_epoch:
            # The router moved the keyspace (reshard flip) under this
            # worker's cached mapping: fail closed to the ring path —
            # the engine routes by the CURRENT mapping — until the
            # worker refreshes and calls note_keymap_epoch.
            return None
        if not 0 <= group < self.num_groups:
            return None
        applied, commit, _base, lease_ns, _leader, _pad = rows[group]
        if mode == "local":
            target = applied
        elif mode == "session":
            if applied < watermark:
                return None                  # engine blocks, we don't
            target = max(applied, watermark)
        elif mode == "follower":
            if applied < commit:
                return None
            target = commit
        elif mode == "linear":
            if lease_ns <= 0 or time.monotonic_ns() >= lease_ns:
                return None                  # no provable lease
            if applied < commit:
                return None
            if time.monotonic_ns() - hdr[8] > PUB_STALE_NS:
                return None                  # publisher heartbeat stale
            # Serve at `applied`, NOT the published commit column: the
            # apply thread publishes applied before acks fire, so it
            # covers every acked write, while commit is only restamped
            # by the ~2ms refresh thread — targeting commit inside that
            # window could miss a just-acked PUT.  applied never runs
            # ahead of true commit (entries apply only after commit),
            # and the applied >= commit guard above keeps the lease
            # evidence sound.
            target = applied
        else:
            return None
        with self._lock:
            rep = self._replicas.get(group)
            if rep is None:
                rep = _GroupReplica(group)
                self._replicas[group] = rep
            if not self._catch_up(rep, target, hdr[6]):
                return None
            try:
                out = rep.sm.query(query)
            except Exception:                # noqa: BLE001
                return None                  # surface SQL errors via ring
            return out, int(rep.sm.applied_index())

    def leader_of(self, group: int) -> int:  # raftlint: fail-closed
        """Published 1-based leader hint (0 unknown), for worker-side
        421 redirects without a ring trip; -0 fail-open to 0."""
        snap = self._snapshot_table()
        if snap is None or not 0 <= group < self.num_groups:
            return 0
        return int(snap[1][group][4])

    def keymap_epoch(self) -> int:
        """The publisher's CURRENT elastic-keyspace mapping version
        (0 when no reshard plane ever published)."""
        hdr = self._read_header_raw()
        return int(hdr[9]) if hdr is not None else 0

    def note_keymap_epoch(self, epoch: int) -> None:
        """The worker refreshed its key->group mapping to `epoch`
        (from /healthz): shm reads revalidate against it."""
        self._kmap_epoch = int(epoch)

    def close(self) -> None:
        try:
            self._mm.close()
        except (BufferError, ValueError):
            pass
