"""Overload-control plane: admission, deadlines, brownout.

Three coordinated defenses against offered load exceeding engine
capacity (the serving-stack gap ISSUE 20 closes):

  - admission control (admission.py OverloadController): bounded
    per-group and per-engine propose budgets enforced at the hostplane
    propose edge, refused with the typed `Overloaded` (HTTP 429 +
    Retry-After, jittered from the observed drain rate);
  - end-to-end deadlines: `X-Raft-Deadline-Ms` converted ONCE at the
    serving edge into device-step units (the PR-9 lease-clock
    discipline — never wall clock on digest-relevant paths) and
    carried through ring record → RaftDB → hostplane staging, so
    expired work is shed before WAL/fsync cost is paid;
  - brownout ladder (admission.py BrownoutGovernor): under sustained
    queue pressure linear reads degrade to lease-only, and — only for
    clients opting in via `X-Raft-Brownout: allow` — to session
    reads, never silently (X-Raft-Served-Mode names what was served).

The plane is attachment-gated like the shm/replica/reshard planes: an
engine without a controller attached (`node.overload is None`) runs
the exact pre-existing code paths — `make chaos SEED=0` digests are
pinned against that (bench_logs/chaos_digests.json).
"""
from raftsql_tpu.overload.admission import (BROWNOUT_LEASE_ONLY,
                                            BROWNOUT_OFF,
                                            BrownoutGovernor,
                                            DeadlineExceeded,
                                            OverloadController,
                                            Overloaded,
                                            deadline_steps,
                                            retry_after_header,
                                            retryable_refusal,
                                            zero_metrics_doc)

__all__ = ["Overloaded", "DeadlineExceeded", "OverloadController",
           "BrownoutGovernor", "BROWNOUT_OFF", "BROWNOUT_LEASE_ONLY",
           "deadline_steps", "retry_after_header", "retryable_refusal",
           "zero_metrics_doc"]
