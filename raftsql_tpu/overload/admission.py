"""Admission control, deadline clocks, and the brownout governor.

The controller is the single owner of every overload DECISION — the
hostplane, ring, HTTP planes and replica tier only report observations
(enqueues, drains, ticks) and obey the typed outcomes (`Overloaded`,
`DeadlineExceeded`, a brownout-served read mode).  Decision functions
are fail-closed (raftlint registry, analysis/config.py): every control
path ends in an explicit return or raise, so "forgot the refusal
branch" is a lint finding, not a melted queue.

Determinism contract: decisions depend only on counters (queue depths,
seeded jitter), never on wall clock — the overload chaos family
digest-compares two runs bit for bit.  The only wall-derived quantity
is the ADVISORY `Retry-After` value (drain-rate EWMA x tick interval),
which is never digested.

Units: deadlines travel as DEVICE STEPS (the PR-9 lease-clock
discipline).  `deadline_steps()` converts the edge's `X-Raft-
Deadline-Ms` budget exactly once; everything below the edge compares
step counters.
"""
from __future__ import annotations

import random
from typing import Optional

# Brownout ladder rungs (BrownoutGovernor.mode).
BROWNOUT_OFF = 0          # serve everything normally
BROWNOUT_LEASE_ONLY = 1   # linear reads: lease fast path only — no
                          # ReadIndex rounds; degrade or refuse


class Overloaded(Exception):
    """Typed admission refusal: the caller must back off.

    Surfaces as HTTP 429 + `Retry-After` on both HTTP planes, as
    ST_OVERLOADED on the propose ring, and on the replica tier's
    write-fallback path.  `retry_after_s` is advisory (jittered from
    the observed queue drain rate); `scope` names which budget refused
    ("group:<g>", "engine", "brownout", "ring", "replica")."""

    def __init__(self, scope: str, retry_after_s: float,
                 detail: str = ""):
        super().__init__(
            f"overloaded ({scope}): "
            + (detail or "admission budget exhausted")
            + f"; retry after {retry_after_s:.3f}s")
        self.scope = scope
        self.retry_after_s = float(retry_after_s)


class DeadlineExceeded(Exception):
    """The request's end-to-end deadline passed before the work was
    done — shed without paying the remaining cost.  `phase` names
    where the shed happened (edge / ring / stage / commit_wait), the
    per-phase attribution the /metrics overload section exports."""

    def __init__(self, phase: str, detail: str = ""):
        super().__init__(f"deadline exceeded at {phase}"
                         + (f": {detail}" if detail else ""))
        self.phase = phase


def deadline_steps(now_step: int, deadline_ms: float,
                   tick_interval_s: float) -> int:
    """Convert an edge deadline budget (milliseconds remaining) into
    an ABSOLUTE device-step deadline — the one wall→step conversion;
    everything below the edge compares step counters (deterministic
    under chaos replay).  Mirrors lease_deadline_s's floor: an untimed
    engine (tick_interval_s == 0, step-per-loop) converts at 0.1 ms
    per step."""
    interval = max(float(tick_interval_s), 1e-4)
    return int(now_step) + max(0, int((float(deadline_ms) / 1000.0)
                                      / interval))


def retry_after_header(retry_after_s: float) -> str:
    """`Retry-After` wire value: decimal seconds (our client parses
    float; integer-second RFC granularity is useless at millisecond
    drain times), floored at 10 ms so a parsed 0 never means "hammer
    immediately"."""
    return f"{max(float(retry_after_s), 0.01):.3f}"


def retryable_refusal(exc: Exception,
                      default_retry_s: float = 1.0):
    """THE unified retryable-refusal mapping, shared by both HTTP
    planes (satellite: the threaded plane's ad-hoc 503s and the aio
    plane must emit one consistent contract):

        Overloaded        -> (429, its jittered retry_after_s)
        anything else     -> (503, default_retry_s)

    Returns (status_code, retry_after_s); the caller renders the
    header via retry_after_header()."""
    if isinstance(exc, Overloaded):
        return 429, exc.retry_after_s
    return 503, float(default_retry_s)


class BrownoutGovernor:
    """Hysteresis ladder over the queue-depth EWMA.

    Enters BROWNOUT_LEASE_ONLY when the depth EWMA crosses `hi`,
    exits at `lo` — the gap prevents mode flapping at the threshold.
    The EWMA is fed once per engine tick (OverloadController
    .note_tick), the same cadence as the PR-8 phase profiler whose
    queue observations it summarizes."""

    def __init__(self, hi: float, lo: float, alpha: float = 0.3):
        if hi <= 0 or lo < 0 or lo >= hi:
            raise ValueError("brownout thresholds need 0 <= lo < hi")
        self.hi = float(hi)
        self.lo = float(lo)
        self.alpha = float(alpha)
        self.ewma = 0.0
        self.mode = BROWNOUT_OFF
        self.transitions = 0

    def note_depth(self, depth: int) -> int:
        """Fold one tick's queue depth; returns the (possibly new)
        mode."""
        self.ewma += self.alpha * (float(depth) - self.ewma)
        if self.mode == BROWNOUT_OFF and self.ewma > self.hi:
            self.mode = BROWNOUT_LEASE_ONLY
            self.transitions += 1
        elif self.mode != BROWNOUT_OFF and self.ewma < self.lo:
            self.mode = BROWNOUT_OFF
            self.transitions += 1
        return self.mode


class OverloadController:
    """Bounded propose budgets + per-phase deadline sheds + brownout.

    Attachment contract (digest neutrality): an engine runs this plane
    only when a controller is assigned to `node.overload`; the default
    None keeps every hot path byte-identical to the pre-overload code.

    Threading: admit/drained/shed_stage run under the hostplane's
    `_prop_lock` (they are called from inside its critical sections);
    note_tick runs on the tick thread; the edge counters are bumped
    from HTTP threads GIL-atomically like every NodeMetrics counter.

    `group_cap` bounds queued-but-unstaged entries per group,
    `total_cap` per engine; 0 disables that budget (depth is still
    tracked for the brownout governor and the queue_depth gauge)."""

    def __init__(self, groups: int, group_cap: int = 0,
                 total_cap: int = 0, seed: int = 0,
                 tick_interval_s: float = 0.001,
                 brownout_hi: Optional[float] = None,
                 brownout_lo: Optional[float] = None):
        self.groups = int(groups)
        self.group_cap = int(group_cap)
        self.total_cap = int(total_cap)
        self.tick_interval_s = max(float(tick_interval_s), 1e-4)
        self._rng = random.Random(seed)     # jitter only, never control
        self._depth = [0] * self.groups
        self.depth_total = 0
        # Drain-rate EWMA (entries per tick): the Retry-After feed.
        self._drain_ewma = 0.0
        self._drained_since_tick = 0
        # Counters (the six required /metrics leaves + per-phase shed).
        self.admitted = 0
        self.rejected = 0
        self.shed_edge = 0
        self.shed_ring = 0
        self.shed_stage = 0
        self.shed_commit_wait = 0
        self.brownouts = 0                  # degraded/refused responses
        self.peak_depth = 0
        hi = brownout_hi if brownout_hi is not None else (
            0.75 * self.total_cap if self.total_cap else float("inf"))
        lo = brownout_lo if brownout_lo is not None else (
            hi / 3.0 if hi != float("inf") else 0.0)
        self.governor = BrownoutGovernor(hi, lo) \
            if hi != float("inf") else None

    # -- admission (under hostplane._prop_lock) ------------------------

    # raftlint: fail-closed
    def admit(self, group: int, n: int):
        """Admit `n` entries into `group`'s propose queue or raise
        Overloaded.  Budgets are checked BEFORE the enqueue, so actual
        queue depth can never exceed the caps (the chaos memory-bound
        invariant measures the real queues, not this bookkeeping)."""
        g = int(group)
        if self.group_cap and self._depth[g] + n > self.group_cap:
            self.rejected += n
            raise Overloaded(f"group:{g}", self.retry_after_s(),
                             f"group queue at {self._depth[g]}"
                             f"/{self.group_cap}")
        if self.total_cap and self.depth_total + n > self.total_cap:
            self.rejected += n
            raise Overloaded("engine", self.retry_after_s(),
                             f"engine queue at {self.depth_total}"
                             f"/{self.total_cap}")
        self._depth[g] += n
        self.depth_total += n
        self.admitted += n
        if self.depth_total > self.peak_depth:
            self.peak_depth = self.depth_total
        return n

    def drained(self, group: int, n: int) -> None:
        """n entries left `group`'s queue toward the device (staged)."""
        self._depth[int(group)] -= n
        self.depth_total -= n
        self._drained_since_tick += n

    def stage_shed(self, group: int, n: int) -> None:
        """n queued entries dropped at staging (expired deadline) —
        the shed that saves WAL/fsync cost."""
        self._depth[int(group)] -= n
        self.depth_total -= n
        self.shed_stage += n

    def reset_depth(self) -> None:
        """The propose queues died with their node (crash/restart):
        re-zero depth bookkeeping; cumulative counters survive."""
        self._depth = [0] * self.groups
        self.depth_total = 0

    # -- deadline sheds ------------------------------------------------

    # raftlint: fail-closed
    def check_deadline(self, now_step: int,
                       deadline_step: Optional[int], phase: str):
        """Shed work whose step deadline already passed; returns True
        (still live) or raises DeadlineExceeded with the phase
        attributed."""
        if deadline_step is None:
            return True
        if int(now_step) <= int(deadline_step):
            return True
        self.note_shed(phase)
        raise DeadlineExceeded(phase,
                               f"step {int(now_step)} past "
                               f"{int(deadline_step)}")

    def note_shed(self, phase: str) -> None:
        if phase == "edge":
            self.shed_edge += 1
        elif phase == "ring":
            self.shed_ring += 1
        elif phase == "stage":
            self.shed_stage += 1
        else:
            self.shed_commit_wait += 1

    # -- tick feed / brownout ------------------------------------------

    def note_tick(self) -> None:
        """Per-engine-tick observation: fold this tick's drain count
        into the rate EWMA and feed the brownout governor the current
        depth."""
        d, self._drained_since_tick = self._drained_since_tick, 0
        self._drain_ewma += 0.3 * (float(d) - self._drain_ewma)
        if self.governor is not None:
            self.governor.note_depth(self.depth_total)

    def brownout_active(self) -> bool:
        return (self.governor is not None
                and self.governor.mode != BROWNOUT_OFF)

    # raftlint: fail-closed
    def brownout_read_path(self, opt_in: bool):
        """Decide how a linear read proceeds when the lease fast path
        is unavailable: outside brownout pay the ReadIndex round
        ("read_index"); inside it, degrade to "session" for clients
        that opted in (X-Raft-Brownout: allow) or refuse typed —
        NEVER a silent stale answer."""
        if not self.brownout_active():
            return "read_index"
        self.brownouts += 1
        if opt_in:
            return "session"
        raise Overloaded(
            "brownout", self.retry_after_s(),
            "linear reads are lease-only under brownout (send "
            "X-Raft-Brownout: allow to accept a session read)")

    # -- advisory backoff ----------------------------------------------

    def retry_after_s(self) -> float:
        """Jittered advisory backoff: the time the CURRENT backlog
        needs to drain at the observed rate, x [0.5, 1.5) jitter so a
        refused client herd does not re-arrive in phase.  Clamped to
        [10 ms, 5 s]; with no drain observed yet, the pessimistic
        clamp ceiling applies."""
        rate = self._drain_ewma                     # entries / tick
        if rate <= 1e-6:
            base = 5.0
        else:
            base = (max(self.depth_total, 1) / rate) \
                * self.tick_interval_s
        base = min(max(base, 0.01), 5.0)
        return base * (0.5 + self._rng.random())

    # -- export --------------------------------------------------------

    def metrics_doc(self) -> dict:
        doc = {
            "admitted": int(self.admitted),
            "rejected": int(self.rejected),
            "shed_edge": int(self.shed_edge),
            "shed_ring": int(self.shed_ring),
            "shed_stage": int(self.shed_stage),
            "shed_commit_wait": int(self.shed_commit_wait),
            "brownouts": int(self.brownouts),
            "queue_depth": int(self.depth_total),
            "queue_depth_peak": int(self.peak_depth),
            "group_cap": int(self.group_cap),
            "total_cap": int(self.total_cap),
            "brownout_active": int(self.brownout_active()),
        }
        return doc


def zero_metrics_doc() -> dict:
    """The overload /metrics section when no controller is attached —
    zeros so the raftsql_overload_* series exist from boot on every
    deployment (scripts/check_prom.py requires them), mirroring the
    replica section's precedent."""
    return {"admitted": 0, "rejected": 0, "shed_edge": 0,
            "shed_ring": 0, "shed_stage": 0, "shed_commit_wait": 0,
            "brownouts": 0, "queue_depth": 0, "queue_depth_peak": 0,
            "group_cap": 0, "total_cap": 0, "brownout_active": 0}
