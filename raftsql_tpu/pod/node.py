"""PodClusterNode — the hostplane tick run by N processes at once.

Execution model (the dry-run rungs; real hardware swaps the device
layer only): every pod process runs the IDENTICAL global device
program over its own mesh — replicated SPMD, the multi-controller
JAX model (DrJAX / Podracer, PAPERS.md) where each controller issues
the same program and per-host behavior differs only in which slice of
the OUTPUT it takes responsibility for.  Here the per-host slice is
the DURABLE plane:

  * compute is replicated — every host holds the full [P, G] device
    state and steps it identically, so `_hard` / `_hints` / `_applied`
    agree bit-for-bit across hosts (and with a single-controller
    MeshClusterNode on the same schedule, the equivalence tier-1 tests
    pin in tests/test_pod.py);
  * durability is sharded — PodShardedWAL materializes WAL directories
    only for the group shards this process OWNS (PodConfig round-robin
    assignment) and absorbs writes for the rest, so each group's whole
    P-peer history lives on exactly one host and the pod's aggregate
    fsync bandwidth scales with hosts;
  * the planes that cross hosts ride ONE per-tick collective
    (pod/transport.py): proposals accepted on any host are all-gathered
    and merged in pod-global sequence order before the dispatch (so
    every host proposes the same batch in the same order — the
    replicated trajectories cannot diverge), the owning host's
    durable-commit acks ride back, and the gather itself is the tick +
    fsync barrier (a host only joins collective t+1 after its durable
    phase for t completed).

Why a group's peers are NOT split across hosts: the P peer rows of one
group form one raft instance whose per-tick messages assume every
sender's WAL fsync preceded the receive (the hostplane contract).
With peer rows on different hosts, a mixed restart (host A at tick t,
host B at tick t-1) would resurrect a half-erased dispatch.  Keeping a
group's peer rows in one host's WAL makes per-group durability
single-host atomic — groups are independent raft instances, so
sharding BY GROUP loses nothing.

Restart model: fail-stop and pod-wide (transport docstring).  At boot
every host replays the shards it owns from local disk, and the pod
all-gathers the serialized GroupLogs so each host rebuilds the FULL
replicated image — the cross-host analogue of ShardedWAL's merged
replay, with the same wrong-shard refusal plus the PODMETA assignment
check (pod/config.py).

Overlap is disabled on the pod (`self._overlap = False`): the
collective is the pipeline barrier, and stashing a durable phase past
it would let this host's disk lag a dispatch other hosts already
observed — exactly the hazard the barrier exists to exclude.
"""
from __future__ import annotations

import base64
import json
import os
import threading
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from raftsql_tpu.config import RaftConfig
from raftsql_tpu.parallel.sharded import GROUPS_AXIS
from raftsql_tpu.pod.config import PodConfig
from raftsql_tpu.pod.transport import make_transport
from raftsql_tpu.runtime.mesh import MeshClusterNode, ShardedWAL
from raftsql_tpu.storage.wal import (DEFAULT_SEGMENT_BYTES, GroupLog,
                                     HardState, WAL, wal_exists)


class _NullShardWAL:
    """The write surface of a group shard OWNED BY ANOTHER POD HOST:
    absorbs every append/hardstate/fsync (that host is the durable
    authority for these groups) and replays nothing.  Keeping the
    surface identical to WAL lets ShardedWAL's routing stay oblivious
    to ownership."""

    def __init__(self) -> None:
        self.obs = None

    def append_ranges(self, groups, starts, counts, terms, datas) -> None:
        pass

    def set_hardstates(self, groups, terms, votes, commits) -> None:
        pass

    def set_conf(self, group, index, kind, voters, joint,
                 learners) -> None:
        pass

    def epoch_mark(self, no, end) -> None:
        pass

    def sync(self) -> None:
        pass

    def compact(self, floors, hard) -> int:
        return 0

    def close(self) -> None:
        pass


class PodShardedWAL(ShardedWAL):
    """ShardedWAL with per-host ownership: real WAL directories for the
    shards this process owns, null sinks for the rest.  Same routed
    write surface, same per-shard replay/repair (which simply never
    find non-owned directories on this host's disk)."""

    def __init__(self, dirname: str, num_shards: int,
                 groups_per_shard: int, owned,
                 segment_bytes: int = DEFAULT_SEGMENT_BYTES):
        self.dirname = dirname
        self.num_shards = num_shards
        self._gl = groups_per_shard
        self.owned = frozenset(owned)
        dirs = self.shard_dirs(dirname, num_shards)
        self.shards = [WAL(dirs[j], segment_bytes=segment_bytes)
                       if j in self.owned else _NullShardWAL()
                       for j in range(num_shards)]
        self._lib = None        # no cross-shard combined native calls

    @property
    def obs(self):
        for s in self.shards:
            if not isinstance(s, _NullShardWAL):
                return s.obs
        return None

    @obs.setter
    def obs(self, tracer) -> None:
        for s in self.shards:
            s.obs = tracer


# -- GroupLog wire form (the boot replay exchange) ----------------------

def encode_group_log(gl: GroupLog) -> dict:
    return {"h": [gl.hard.term, gl.hard.vote, gl.hard.commit],
            "s": gl.start, "st": gl.start_term,
            "c": list(gl.conf) if gl.conf is not None else None,
            "d": ([gl.dedup[0], [list(x) for x in gl.dedup[1]]]
                  if gl.dedup is not None else None),
            "e": [[t, base64.b64encode(d).decode()]
                  for (t, d) in gl.entries]}


def decode_group_log(doc: dict) -> GroupLog:
    gl = GroupLog(hard=HardState(*(int(x) for x in doc["h"])),
                  start=int(doc["s"]), start_term=int(doc["st"]))
    gl.entries = [(int(t), base64.b64decode(b)) for t, b in doc["e"]]
    if doc["c"] is not None:
        gl.conf = tuple(int(x) for x in doc["c"])
    if doc["d"] is not None:
        gl.dedup = (int(doc["d"][0]),
                    [(int(a), int(b)) for a, b in doc["d"][1]])
    return gl


class PodClusterNode(MeshClusterNode):
    """MeshClusterNode whose durable plane is one slice of a pod.

    Construction joins the pod (transport connect + replay exchange)
    and therefore BLOCKS until all `pod.procs` processes arrive — the
    pod is one program.  `transport` can be injected for tests; by
    default it is built from the PodConfig."""

    def __init__(self, pod: PodConfig, cfg: RaftConfig, data_dir: str,
                 mesh, transport=None, seed: Optional[int] = None,
                 connect_timeout_s: float = 30.0,
                 io_timeout_s: float = 600.0):
        gg = mesh.shape[GROUPS_AXIS]
        pod.validate(gg)
        if cfg.num_groups % gg:
            raise ValueError(f"num_groups {cfg.num_groups} not "
                             f"divisible by group shards {gg}")
        self.pod = pod
        self._pod_owned: Set[int] = set(pod.owned_shards(gg))
        pod.check_meta(data_dir, gg)
        self._pod_transport = transport if transport is not None \
            else make_transport(pod.procs, pod.proc_id, pod.coordinator,
                                connect_timeout_s=connect_timeout_s,
                                io_timeout_s=io_timeout_s)
        # Client-plane buffers: proposals offered on THIS host wait
        # here for the next collective; seqs are origin-strided so the
        # pod-global merge order is total without coordination.
        self._pod_mu = threading.Lock()
        self._pod_offers: List[Tuple[int, int, bytes]] = []  # raftlint: guarded-by=_pod_mu
        self._pod_acks_out: List[int] = []   # raftlint: guarded-by=_pod_mu
        self._pod_acked: Set[int] = set()    # raftlint: guarded-by=_pod_mu
        self._pod_seq = pod.proc_id
        # Boot replay exchange: local owned shards -> all-gather -> the
        # full per-peer-dir image, consumed through the hostplane
        # replay seams during super().__init__, then freed.
        g_loc = cfg.num_groups // gg
        self._pod_replay: Optional[Dict[str, Dict[int, GroupLog]]] = \
            self._pod_exchange_replay(cfg, data_dir, g_loc)
        super().__init__(cfg, data_dir, mesh, seed)
        self._pod_replay = None
        # The collective is the pipeline barrier: durable phase t must
        # complete before this host contributes gather t+1, so the
        # double-buffered stash (overlap) is disabled; tick() below
        # also retires any deferred publish before returning, so
        # in-memory == durable == published at every barrier.
        self._overlap = False

    # -- boot: the cross-host replay exchange ---------------------------

    def _pod_exchange_replay(self, cfg: RaftConfig, data_dir: str,
                             g_loc: int) -> Dict[str, Dict[int, GroupLog]]:
        contrib: Dict[str, Dict[str, dict]] = {}
        for p in range(cfg.num_peers):
            pd = os.path.join(data_dir, f"p{p + 1}")
            logs: Dict[int, GroupLog] = {}
            for j in sorted(self._pod_owned):
                sd = os.path.join(pd, f"s{j}")
                if not wal_exists(sd):
                    continue
                for g, gl in WAL.replay(sd).items():
                    if g // g_loc != j:
                        raise ValueError(
                            f"{pd}: group {g} replayed from shard {j} "
                            f"but belongs to shard {g // g_loc} — this "
                            "WAL was written under a different "
                            "group-shard count (re-sharding an "
                            "existing data dir is unsupported)")
                    logs[g] = gl
            if logs:
                contrib[str(p)] = {str(g): encode_group_log(gl)
                                   for g, gl in logs.items()}
        blob = json.dumps(contrib, sort_keys=True,
                          separators=(",", ":")).encode()
        parts = self._pod_transport.gather("replay", blob)
        merged: Dict[int, Dict[int, GroupLog]] = \
            {p: {} for p in range(cfg.num_peers)}
        for part in parts:
            if not part:
                continue
            doc = json.loads(part.decode())
            for ps, groups in doc.items():
                p = int(ps)
                for gs, gd in groups.items():
                    g = int(gs)
                    if g in merged[p]:
                        raise ValueError(
                            f"group {g} (peer {p + 1}) replayed by two "
                            "pod hosts — overlapping shard ownership; "
                            "the PODMETA assignment check should have "
                            "refused this layout")
                    merged[p][g] = decode_group_log(gd)
        return {os.path.join(data_dir, f"p{p + 1}"): merged[p]
                for p in range(cfg.num_peers)}

    # -- hostplane seams ------------------------------------------------

    def _new_wal(self, dirname: str) -> PodShardedWAL:
        return PodShardedWAL(dirname, self._gg, self._g_loc,
                             self._pod_owned,
                             segment_bytes=self.cfg.wal_segment_bytes)

    def _wal_exists(self, dirname: str) -> bool:
        if self._pod_replay is not None:
            return bool(self._pod_replay.get(dirname))
        return super()._wal_exists(dirname)

    def _wal_replay(self, dirname: str):
        if self._pod_replay is not None:
            return self._pod_replay.get(dirname, {})
        return super()._wal_replay(dirname)

    # (_wal_repair_epochs inherited: it walks this host's shard dirs
    # and repairs the ones that exist — non-owned shards have no local
    # directory.  The pod pins steps-per-dispatch to 1 via the mesh
    # runtime, so dispatch epoch framing is never written anyway.)

    # -- ownership ------------------------------------------------------

    def group_owner(self, group: int) -> int:
        """proc_id of the host that owns `group`'s durable plane (and
        therefore serves it — server/main.py PodRaftDB)."""
        return self.pod.shard_owner(group // self._g_loc)

    def owns_group(self, group: int) -> bool:
        return (group // self._g_loc) in self._pod_owned

    def owned_groups(self) -> np.ndarray:
        if not self._pod_owned:
            return np.zeros(0, np.int64)
        return np.concatenate(
            [np.arange(j * self._g_loc, (j + 1) * self._g_loc)
             for j in sorted(self._pod_owned)])

    # -- client plane ----------------------------------------------------

    def pod_propose(self, group: int, payloads) -> List[int]:
        """Offer payloads to the pod and return their pod-global seqs
        (origin-strided).  They are proposed — on EVERY host, in seq
        order — at the next collective; the ack for a seq arrives via
        pod_take_acked() once the owning host's durable commit covered
        it."""
        seqs: List[int] = []
        with self._pod_mu:
            for d in payloads:
                seqs.append(self._pod_seq)
                self._pod_offers.append(
                    (self._pod_seq, int(group), bytes(d)))
                self._pod_seq += self.pod.procs
        self._work_evt.set()
        return seqs

    def propose_many(self, group: int, payloads) -> None:
        self.pod_propose(group, payloads)

    def pod_send_ack(self, seqs) -> None:
        """Owner-side: queue durable-commit acks to ride the next
        collective back to their origins.  Callers (the dry-run driver,
        the --pod server) invoke this only AFTER the committed entry is
        covered by this host's fsync barrier — publish follows the
        barrier, so acking off the publish stream is sound."""
        seqs = list(seqs)
        with self._pod_mu:
            self._pod_acks_out.extend(int(s) for s in seqs)
        self.metrics.pod_acks_tx += len(seqs)

    def pod_take_acked(self) -> Set[int]:
        """Origin-side: drain the set of this host's seqs acked by
        their owners since the last call."""
        with self._pod_mu:
            out, self._pod_acked = self._pod_acked, set()
        return out

    # -- the pod tick ----------------------------------------------------

    def tick(self) -> None:
        import time as _t
        t0 = _t.monotonic()
        with self._pod_mu:
            offers, self._pod_offers = self._pod_offers, []
            acks, self._pod_acks_out = self._pod_acks_out, []
        doc = {"p": [[s, g, base64.b64encode(d).decode()]
                     for (s, g, d) in offers],
               "a": acks}
        blob = json.dumps(doc, sort_keys=True,
                          separators=(",", ":")).encode()
        parts = self._pod_transport.gather(f"tick:{self._tick_no}", blob)
        merged: List[Tuple[int, int, bytes]] = []
        for part in parts:
            if not part:
                continue
            d = json.loads(part.decode())
            merged.extend((int(s), int(g), base64.b64decode(b))
                          for s, g, b in d["p"])
            for s in d["a"]:
                if self.pod.seq_origin(int(s)) == self.pod.proc_id:
                    self.metrics.pod_acks_rx += 1
                    with self._pod_mu:
                        self._pod_acked.add(int(s))
        # Pod-global proposal order: seqs are origin-strided ints, so
        # sorting gives every host the identical propose sequence —
        # the replicated trajectories cannot diverge, and a
        # single-controller run feeding the same global order is
        # bit-equivalent (tests/test_pod.py pins it).
        merged.sort(key=lambda x: x[0])
        for s, g, data in merged:
            if self.pod.seq_origin(s) != self.pod.proc_id:
                self.metrics.pod_proposals_routed += 1
            super().propose_many(g, [data])
        self.metrics.pod_gathers += 1
        self.metrics.pod_gather_wait_ms += (_t.monotonic() - t0) * 1e3
        tr = self._pod_transport
        self.metrics.pod_bytes_tx = int(getattr(tr, "bytes_tx", 0))
        self.metrics.pod_bytes_rx = int(getattr(tr, "bytes_rx", 0))
        super().tick()
        # Drain the tick fully before the next collective: a serial
        # host's deferred publish (base-class dispatch overlap) would
        # otherwise externalize tick t's commits only during tick t+1,
        # after other hosts already advanced past the barrier.
        if self._pending_pinfo is not None:
            self._publish(self._pending_pinfo)
            self._pending_pinfo = None
        self.publish_flush()

    # -- observability ---------------------------------------------------

    def pod_doc(self) -> dict:
        """The /healthz + /metrics `pod` section: topology, ownership,
        and transport counters for THIS host."""
        tr = self._pod_transport
        return {"procs": self.pod.procs,
                "proc_id": self.pod.proc_id,
                "coordinator": self.pod.coordinator,
                "hosts": list(self.pod.hosts),
                "owned_shards": sorted(self._pod_owned),
                "owned_groups": len(self._pod_owned) * self._g_loc,
                "groups_per_shard": self._g_loc,
                "gathers": int(getattr(tr, "gathers", 0)),
                "bytes_tx": int(getattr(tr, "bytes_tx", 0)),
                "bytes_rx": int(getattr(tr, "bytes_rx", 0))}

    def stop(self) -> None:
        try:
            super().stop()
        finally:
            self._pod_transport.close()
