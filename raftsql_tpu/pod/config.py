"""PodConfig — the declarative description of a multi-host pod.

A pod is N host processes that jointly own one cluster: process i owns
a subset of the group shards (its WAL dirs + its SQLite files), every
process runs the same device program, and a per-tick collective keeps
the processes lockstepped (pod/transport.py).  The config is frozen
and pure data so every process — and the chaos nemesis that respawns
processes — can reconstruct the identical pod from (procs, proc_id,
coordinator) alone.

Shard ownership is round-robin over the group-shard axis
(`owner(j) = j % procs`): any procs <= group_shards layout works, the
assignment is a pure function of the two counts, and a host's owned
blocks interleave with its peers' so a host loss degrades every region
of the keyspace a little instead of one region entirely.

`PODMETA` (written next to the mesh runtime's `MESHMETA`) pins the
assignment a data dir was written under: a host restarted with a shard
assignment that disagrees with its on-disk layout is REFUSED, the
cross-host analogue of the mesh re-shard refusal — adopting another
host's dirs silently would double-own groups and fork history.
"""
from __future__ import annotations

import dataclasses
import json
import os
from typing import List, Optional, Tuple

POD_META = "PODMETA"


@dataclasses.dataclass(frozen=True)
class PodConfig:
    """One pod process's view of the whole pod.

    procs        total host processes in the pod
    proc_id      this process (0-based; 0 is the collective coordinator)
    coordinator  "host:port" the coordinator listens on ("" = in-process
                 LocalPodTransport, only valid for procs == 1)
    hosts        optional HTTP base URLs of every pod host, in proc_id
                 order — the routing table /healthz exports so a client
                 pointed at any one host can sweep the whole pod
    """

    procs: int = 1
    proc_id: int = 0
    coordinator: str = ""
    hosts: Tuple[str, ...] = ()

    def __post_init__(self) -> None:
        if self.procs <= 0:
            raise ValueError(f"pod needs >= 1 process, got {self.procs}")
        if not 0 <= self.proc_id < self.procs:
            raise ValueError(f"proc_id {self.proc_id} outside pod of "
                             f"{self.procs}")
        if self.procs > 1 and not self.coordinator:
            raise ValueError("a multi-process pod needs a coordinator "
                             "address (host:port)")
        if self.hosts and len(self.hosts) != self.procs:
            raise ValueError(f"hosts table has {len(self.hosts)} "
                             f"entries for {self.procs} processes")

    @property
    def is_coordinator(self) -> bool:
        return self.proc_id == 0

    def validate(self, group_shards: int) -> None:
        if self.procs > group_shards:
            raise ValueError(
                f"pod of {self.procs} processes over {group_shards} "
                "group shards: every process must own >= 1 shard")

    def shard_owner(self, shard: int) -> int:
        return shard % self.procs

    def owned_shards(self, group_shards: int) -> List[int]:
        return [j for j in range(group_shards)
                if self.shard_owner(j) == self.proc_id]

    def seq_origin(self, seq: int) -> int:
        """Which process originated a pod-global proposal sequence
        number (origin-strided allocation: origin + k * procs)."""
        return seq % self.procs

    # -- jax.distributed (real multi-host fleets) -----------------------

    def init_distributed(self) -> None:
        """`jax.distributed.initialize` from this config — the real
        multi-host entry point (DrJAX/Podracer-style multi-controller
        fleets), where every process sees the global device set and the
        device step runs as ONE SPMD program over a hybrid mesh.

        The dry-run rungs (pod/dryrun.py, `JAX_PLATFORMS=cpu`) do NOT
        call this: each local process replicates the global program on
        its own forced host devices instead (pod/node.py), which needs
        no cross-process XLA runtime.  Opt in with
        RAFTSQL_POD_JAX_DISTRIBUTED=1 on hardware."""
        import jax
        jax.distributed.initialize(
            coordinator_address=self.coordinator,
            num_processes=self.procs, process_id=self.proc_id)

    # -- PODMETA --------------------------------------------------------

    def meta_doc(self, group_shards: int) -> dict:
        return {"procs": self.procs, "proc_id": self.proc_id,
                "group_shards": group_shards,
                "owned": self.owned_shards(group_shards)}

    def check_meta(self, data_dir: str, group_shards: int) -> None:
        """Refuse a data dir written under a different pod shard
        assignment: the per-shard WAL layout on THIS host holds exactly
        the groups this process owned when the records were written, so
        a changed assignment would silently drop (or double-own) group
        histories across hosts.  Same contract as MESHMETA, one level
        up the hierarchy."""
        os.makedirs(data_dir, exist_ok=True)
        path = os.path.join(data_dir, POD_META)
        doc = self.meta_doc(group_shards)
        if os.path.exists(path):
            with open(path, "r", encoding="utf-8") as f:
                meta = json.load(f)
            if meta != doc:
                raise ValueError(
                    f"{data_dir}: written under pod assignment {meta}, "
                    f"opened with {doc} — changing a host's shard "
                    "assignment over an existing data dir is "
                    "unsupported; use a fresh dir (or the original "
                    "assignment)")
        else:
            with open(path, "w", encoding="utf-8") as f:
                json.dump(doc, f)

    @staticmethod
    def read_meta(data_dir: str) -> Optional[dict]:
        path = os.path.join(data_dir, POD_META)
        if not os.path.exists(path):
            return None
        with open(path, "r", encoding="utf-8") as f:
            return json.load(f)
