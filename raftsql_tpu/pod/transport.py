"""The pod collective: a per-tick all-gather over host boundaries.

One primitive carries every cross-host plane the pod needs:

    gather(tag, payload) -> [payload_0, ..., payload_{N-1}]

Each process contributes one byte blob per tick and receives every
process's blob, indexed by proc id.  That single collective is

  * the PROPOSE plane — a proposal accepted on any host rides its
    origin's contribution and lands, merged in pod-global sequence
    order, on every host (including the one owning the group's shard);
  * the ACK plane — the owning host's durable-commit acknowledgements
    ride back the same way;
  * the TICK + FSYNC BARRIER — a process only contributes tick t+1's
    gather after finishing tick t's durable phase, so no host's fsync
    can lag the dispatch it framed (the `multihost_utils`-style sync
    point, implemented on host sockets because it synchronizes the
    HOST plane, not device math);
  * the REPLAY exchange at boot (pod/node.py): each host contributes
    the shards it replayed from local disk and receives the full
    cluster image.

Topology is a coordinator star (proc 0 accepts N-1 connections,
collects, broadcasts) — one round trip per tick, no peer discovery.
Failure model is FAIL-STOP AND POD-WIDE: any socket loss (a SIGKILLed
member, a dead coordinator, a partition) raises PodPeerLost, and the
process exits — a pod is one SPMD program, and one host dying kills
the program; the supervisor (chaos/pod.py, or an operator) restarts
the pod, which rebuilds from the merged on-disk replay.  The
coordinator broadcasts an explicit abort frame to survivors first so
they fail fast instead of timing out.

`LocalPodTransport` is the procs == 1 degenerate pod (gather returns
your own contribution) — it lets every pod code path run in-process
for tests and for the `--pod` server's single-host mode.
"""
from __future__ import annotations

import base64
import json
import socket
import struct
import time
from typing import Dict, List, Optional

# One frame per process per collective; 64 MiB bounds a malicious or
# corrupt length prefix, far above any real replay contribution.
_FRAME_LIMIT = 64 << 20
_ABORT_TAG = "!abort"


class PodPeerLost(RuntimeError):
    """A pod member (or the coordinator) is gone: the collective cannot
    complete, and this process must exit so the supervisor can restart
    the pod.  Fail-closed — never proceed on a partial gather."""


class LocalPodTransport:
    """The one-process pod: every collective is the identity."""

    procs = 1
    proc_id = 0

    def __init__(self) -> None:
        self.bytes_tx = 0
        self.bytes_rx = 0
        self.gathers = 0

    def gather(self, tag: str, payload: bytes) -> List[bytes]:
        self.gathers += 1
        return [payload]

    def barrier(self, tag: str) -> None:
        self.gathers += 1

    def close(self) -> None:
        pass


def _send_frame(sock: socket.socket, doc: dict) -> int:
    blob = json.dumps(doc, sort_keys=True,
                      separators=(",", ":")).encode()
    try:
        sock.sendall(struct.pack(">I", len(blob)) + blob)
    except OSError as e:
        raise PodPeerLost(f"pod send failed: {e!r}") from e
    return len(blob) + 4


def _recv_frame(sock: socket.socket) -> dict:
    def read_exact(n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            try:
                chunk = sock.recv(n - len(buf))
            except OSError as e:
                raise PodPeerLost(f"pod recv failed: {e!r}") from e
            if not chunk:
                raise PodPeerLost("pod peer closed the connection")
            buf += chunk
        return buf

    (ln,) = struct.unpack(">I", read_exact(4))
    if ln > _FRAME_LIMIT:
        raise PodPeerLost(f"pod frame length {ln} over limit")
    doc = json.loads(read_exact(ln).decode())
    if doc.get("tag") == _ABORT_TAG:
        raise PodPeerLost("pod aborted by coordinator "
                          f"({doc.get('why', 'peer lost')})")
    return doc


class TcpPodTransport:
    """The coordinator-star collective over localhost/DCN TCP sockets.

    Lockstep protocol: every process calls gather(tag, ...) with the
    SAME tag sequence (the pod tick loop guarantees it), so frames
    never interleave across collectives — a mismatched tag is a
    protocol bug and raises immediately rather than mis-merging
    planes.  Thread model: one thread per process drives the
    collective (the tick thread); no internal locking is needed."""

    def __init__(self, procs: int, proc_id: int, coordinator: str,
                 connect_timeout_s: float = 30.0,
                 io_timeout_s: float = 600.0):
        if procs < 2:
            raise ValueError("TcpPodTransport needs >= 2 processes; "
                             "use LocalPodTransport for procs == 1")
        self.procs = procs
        self.proc_id = proc_id
        self.coordinator = coordinator
        self.bytes_tx = 0
        self.bytes_rx = 0
        self.gathers = 0
        self._io_timeout_s = io_timeout_s
        self._closed = False
        host, port = coordinator.rsplit(":", 1)
        if proc_id == 0:
            self._peers = self._accept_members(host, int(port),
                                               connect_timeout_s)
            self._conn: Optional[socket.socket] = None
        else:
            self._conn = self._dial(host, int(port), connect_timeout_s)
            self._peers = {}

    # -- connection setup ----------------------------------------------

    def _accept_members(self, host: str, port: int,
                        timeout_s: float) -> Dict[int, socket.socket]:
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host, port))
        srv.listen(self.procs)
        srv.settimeout(timeout_s)
        peers: Dict[int, socket.socket] = {}
        try:
            while len(peers) < self.procs - 1:
                try:
                    conn, _ = srv.accept()
                except socket.timeout as e:
                    raise PodPeerLost(
                        f"pod formation timed out: {len(peers) + 1} of "
                        f"{self.procs} processes present") from e
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                conn.settimeout(self._io_timeout_s)
                reg = _recv_frame(conn)
                p = int(reg["proc"])
                if reg.get("tag") != "!register" or \
                        not 0 < p < self.procs or p in peers:
                    raise PodPeerLost(f"bad pod registration: {reg}")
                peers[p] = conn
        finally:
            srv.close()
        return peers

    def _dial(self, host: str, port: int,
              timeout_s: float) -> socket.socket:
        deadline = time.monotonic() + timeout_s
        last: Optional[Exception] = None
        while time.monotonic() < deadline:
            try:
                conn = socket.create_connection((host, port), timeout=2.0)
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                conn.settimeout(self._io_timeout_s)
                self.bytes_tx += _send_frame(
                    conn, {"tag": "!register", "proc": self.proc_id})
                return conn
            except OSError as e:   # coordinator not up yet: retry
                last = e
                time.sleep(0.05)
        raise PodPeerLost(f"could not reach pod coordinator "
                          f"{host}:{port}: {last!r}")

    # -- the collective ------------------------------------------------

    def gather(self, tag: str, payload: bytes) -> List[bytes]:
        self.gathers += 1
        if self.proc_id == 0:
            return self._gather_coordinator(tag, payload)
        return self._gather_member(tag, payload)

    def _gather_coordinator(self, tag: str,
                            payload: bytes) -> List[bytes]:
        parts: List[Optional[bytes]] = [None] * self.procs
        parts[0] = payload
        try:
            for p, conn in self._peers.items():
                doc = _recv_frame(conn)
                self.bytes_rx += len(doc.get("data", ""))
                if doc.get("tag") != tag or int(doc.get("proc")) != p:
                    raise PodPeerLost(
                        f"pod collective desync: expected {tag!r} from "
                        f"proc {p}, got {doc.get('tag')!r} from "
                        f"{doc.get('proc')}")
                parts[p] = base64.b64decode(doc["data"])
        except PodPeerLost as e:
            self._abort_survivors(repr(e))
            raise
        out = {"tag": tag,
               "parts": [base64.b64encode(b or b"").decode()
                         for b in parts]}
        for conn in self._peers.values():
            self.bytes_tx += _send_frame(conn, out)
        return [b if b is not None else b"" for b in parts]

    def _gather_member(self, tag: str, payload: bytes) -> List[bytes]:
        self.bytes_tx += _send_frame(
            self._conn, {"tag": tag, "proc": self.proc_id,
                         "data": base64.b64encode(payload).decode()})
        doc = _recv_frame(self._conn)
        if doc.get("tag") != tag:
            raise PodPeerLost(f"pod collective desync: expected "
                              f"{tag!r}, got {doc.get('tag')!r}")
        parts = [base64.b64decode(x) for x in doc["parts"]]
        self.bytes_rx += sum(len(x) for x in parts)
        if len(parts) != self.procs:
            raise PodPeerLost(f"pod gather returned {len(parts)} parts "
                              f"for {self.procs} processes")
        return parts

    def barrier(self, tag: str) -> None:
        self.gather(tag, b"")

    def _abort_survivors(self, why: str) -> None:
        """Best-effort fail-fast fan-out: tell every still-connected
        member the pod is dead so it exits now instead of at its io
        timeout.  Errors here are ignored — we are already failing."""
        for conn in self._peers.values():
            try:
                _send_frame(conn, {"tag": _ABORT_TAG, "why": why})
            except Exception:
                pass

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for conn in self._peers.values():
            try:
                conn.close()
            except OSError:
                pass
        if self._conn is not None:
            try:
                self._conn.close()
            except OSError:
                pass


def make_transport(procs: int, proc_id: int, coordinator: str,
                   connect_timeout_s: float = 30.0,
                   io_timeout_s: float = 600.0):
    if procs == 1:
        return LocalPodTransport()
    return TcpPodTransport(procs, proc_id, coordinator,
                           connect_timeout_s=connect_timeout_s,
                           io_timeout_s=io_timeout_s)
