"""Multi-host pod runtime: N host processes jointly own the cluster.

Every plane before this package funneled through ONE Python controller
process (ROADMAP's first open item).  The pod runtime breaks that
assumption: N host processes — one per mesh slice — each run the SAME
hostplane tick, lockstepped by a per-tick collective, while DURABILITY
is sharded across hosts (each host fsyncs only the group shards it
owns).  See pod/node.py for the execution model and its equivalence
argument, pod/transport.py for the collective, and pod/dryrun.py for
the dry-run rungs (`JAX_PLATFORMS=cpu`, N local processes).
"""
from raftsql_tpu.pod.config import POD_META, PodConfig
from raftsql_tpu.pod.node import PodClusterNode, PodShardedWAL
from raftsql_tpu.pod.transport import (LocalPodTransport, PodPeerLost,
                                       TcpPodTransport)

__all__ = ["POD_META", "PodConfig", "PodClusterNode", "PodShardedWAL",
           "LocalPodTransport", "PodPeerLost", "TcpPodTransport"]
