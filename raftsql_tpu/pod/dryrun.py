"""Dry-run pod driver: N local processes, CPU devices, one box.

The first two rungs of the pod ladder (ISSUE/README):

  rung 1 — dry-run multi-process: N processes of THIS module form a
  pod over localhost sockets and run a seeded workload;

  rung 2 — bit-for-bit equivalence: each process dumps its hard
  states, publish cursors, leader hints and applied KV stream, and
  tests/test_pod.py compares every host's dump against a
  single-controller MeshClusterNode driven through the SAME global
  workload (and against each other).

Launch (one line per process, any order; proc 0 is the coordinator):

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
      python -m raftsql_tpu.pod.dryrun --procs 2 --proc-id 0 \\
        --coord 127.0.0.1:19317 --data-dir /tmp/pod/h0 --ticks 80 \\
        --out /tmp/pod/h0.json
    ... --proc-id 1 --data-dir /tmp/pod/h1 --out /tmp/pod/h1.json

`--mode bench` times the same loop and reports commits/s plus the
per-phase profiler shares with the pod gather wait broken out, so the
cross-host hop cost is attributed, not guessed (the
BENCH_CONFIG=multichip BENCH_POD_PROCS=N rung drives it).
"""
from __future__ import annotations

import argparse
import hashlib
import json
import os
import queue
import sys
from typing import List, Tuple


def seeded_workload(seed: int, ticks: int, num_groups: int,
                    rate: float = 0.4) -> List[List[Tuple[int, int, bytes]]]:
    """The pod dry-run workload: per tick, a seeded subset of groups
    each gets one `SET k<g> v<seq>` — the same shape tests/test_mesh.py
    drives the fused<->mesh equivalence with.  Returns per-tick lists
    of (global_index, group, payload); in a pod of N processes, item i
    is OFFERED by process i % N, and the gather's seq-order merge
    reassembles exactly this global order on every host."""
    import numpy as np
    rng = np.random.default_rng(seed)
    out: List[List[Tuple[int, int, bytes]]] = []
    idx = seq = 0
    for _ in range(ticks):
        tick_items: List[Tuple[int, int, bytes]] = []
        for g in range(num_groups):
            if rng.random() < rate:
                tick_items.append(
                    (idx, g, f"SET k{g} v{seq}".encode()))
                idx += 1
                seq += 1
        out.append(tick_items)
    return out


def drain_commits(node, peer: int = 0) -> List[tuple]:
    """Drain peer 0's commit stream into (group, index, payload) rows
    (the applied-KV stream the equivalence contract compares)."""
    from raftsql_tpu.runtime.db import _expand_commit_item
    out: List[tuple] = []
    q = node.commit_q(peer)
    while True:
        try:
            item = q.get_nowait()
        except queue.Empty:
            break
        if item is None or not isinstance(item, tuple):
            continue
        out.extend(_expand_commit_item(item))
    return out


def state_doc(node, applied_rows: List[tuple]) -> dict:
    """The equivalence dump: full hard states / cursors / hints plus
    the applied stream, and a digest of the lot for quick cross-host
    comparison."""
    import base64

    import numpy as np
    # Canonical order (group, index): per-group streams are FIFO on
    # every runtime, but the INTERLEAVING across group shards depends
    # on the publish mode (inline serial vs per-shard workers), which
    # the host's core count selects — sorting removes exactly that
    # execution detail and nothing semantic.
    rows = sorted([int(g), int(i),
                   d.decode("utf-8", "replace")
                   if isinstance(d, (bytes, bytearray)) else str(d)]
                  for (g, i, d) in applied_rows)
    doc = {
        "hard": base64.b64encode(
            np.ascontiguousarray(node._hard).tobytes()).decode(),
        "applied": base64.b64encode(
            np.ascontiguousarray(node._applied).tobytes()).decode(),
        "hints": [int(x) for x in node._hints],
        "kv_stream": rows,
    }
    blob = json.dumps(doc, sort_keys=True,
                      separators=(",", ":")).encode()
    doc["digest"] = hashlib.sha256(blob).hexdigest()[:16]
    return doc


def build_pod_node(args, transport=None):
    from raftsql_tpu.config import RaftConfig
    from raftsql_tpu.pod.config import PodConfig
    from raftsql_tpu.pod.node import PodClusterNode
    from raftsql_tpu.runtime.mesh import MeshConfig
    pod = PodConfig(procs=args.procs, proc_id=args.proc_id,
                    coordinator=args.coord or "")
    if os.environ.get("RAFTSQL_POD_JAX_DISTRIBUTED") == "1":
        pod.init_distributed()
    cfg = RaftConfig(num_groups=args.groups, num_peers=args.peers,
                     log_window=32, max_entries_per_msg=4,
                     election_ticks=10, heartbeat_ticks=1,
                     tick_interval_s=0.0, seed=7)
    gg = args.group_shards
    if gg <= 0:
        gg = MeshConfig.for_groups(cfg).group_shards
    mesh = MeshConfig(peer_shards=1, group_shards=gg).build()
    node = PodClusterNode(pod, cfg, args.data_dir, mesh,
                          transport=transport, seed=3,
                          connect_timeout_s=args.connect_timeout)
    return node, cfg


def run_equiv(args) -> dict:
    node, cfg = build_pod_node(args)
    applied: List[tuple] = []
    try:
        wl = seeded_workload(args.seed, args.ticks, cfg.num_groups)
        for t in range(args.ticks):
            for i, g, payload in wl[t]:
                if i % args.procs == args.proc_id:
                    node.pod_propose(g, [payload])
            node.tick()
            applied.extend(drain_commits(node))
        doc = state_doc(node, applied)
        doc["proc_id"] = args.proc_id
        return doc
    finally:
        node.stop()


def run_bench(args) -> dict:
    import time
    node, cfg = build_pod_node(args)
    try:
        wl = seeded_workload(args.seed, args.ticks, cfg.num_groups)
        # Warmup: elections + compile fall out of the timed window.
        for _ in range(10):
            node.tick()
        drain_commits(node)
        t0 = time.perf_counter()
        commits = 0
        for t in range(args.ticks):
            for i, g, payload in wl[t]:
                if i % args.procs == args.proc_id:
                    node.pod_propose(g, [payload])
            node.tick()
            commits += len(drain_commits(node))
        dt = time.perf_counter() - t0
        snap = node.metrics.snapshot()
        doc = {"proc_id": args.proc_id, "ticks": args.ticks,
               "commits": commits,
               "commits_per_s": round(commits / max(dt, 1e-9), 1),
               "wall_s": round(dt, 3),
               "phase_ms_per_tick": snap["phase_ms_per_tick"],
               "pod": snap["pod"],
               "pod_wait_ms_per_tick": round(
                   snap["pod"]["gather_wait_ms"]
                   / max(snap["pod"]["gathers"], 1), 4)}
        if node.prof is not None:
            doc["phase_shares"] = node.prof.shares()
        return doc
    finally:
        node.stop()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="raftsql pod dry-run driver (one pod process)")
    ap.add_argument("--procs", type=int, default=1)
    ap.add_argument("--proc-id", type=int, default=0)
    ap.add_argument("--coord", default="",
                    help="coordinator host:port (procs > 1)")
    ap.add_argument("--data-dir", required=True)
    ap.add_argument("--groups", type=int, default=8)
    ap.add_argument("--peers", type=int, default=3)
    ap.add_argument("--group-shards", type=int, default=0,
                    help="0 = widest fit for the visible devices")
    ap.add_argument("--ticks", type=int, default=80)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--mode", choices=("equiv", "bench"),
                    default="equiv")
    ap.add_argument("--connect-timeout", type=float, default=30.0)
    ap.add_argument("--out", default="",
                    help="write the result doc here (default stdout)")
    args = ap.parse_args(argv)
    doc = run_equiv(args) if args.mode == "equiv" else run_bench(args)
    blob = json.dumps(doc, sort_keys=True)
    if args.out:
        tmp = args.out + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(blob)
        os.replace(tmp, args.out)
    else:
        print(blob)
    return 0


if __name__ == "__main__":
    sys.exit(main())
