"""Witness state machine: a voter that owns no data.

A witness peer (config.py quorum geometry) is a full quorum citizen on
the durability plane — it votes, grants prevotes, accepts appends and
fsyncs its WAL — but it never applies, never serves a read, and never
leads (core/step.py gates its campaign timer).  This state machine is
what runtime/db.py installs in place of the real sm_factory on a
witness replica: the SQLite factory is never invoked, so no shard file
or directory ever exists, and committed payloads are discarded on
arrival — they are already durable in the WAL, which is the only thing
a witness owes the cluster.

This is the half-replica of Cheap Paxos / the witness in etcd's
learner-adjacent designs: N-1 full replicas plus a witness gives the
same fault tolerance as N full replicas for half the apply and shard
fsync cost, as long as the witness is never counted on to SERVE.
"""
from __future__ import annotations

from typing import Optional


class WitnessQueryError(ValueError):
    """A read reached a witness replica.  ValueError so the HTTP
    planes answer 400 without a dedicated handler."""


class WitnessStateMachine:
    # No durable snapshot: a witness must never gate WAL compaction on
    # its (nonexistent) applied state (runtime/db.py checks this flag).
    has_durable_snapshot = False

    def __init__(self, path_or_group="", *_a, **_k):
        # Accepts and ignores the sm_factory signature (group index or
        # path): nothing is created anywhere.
        self._applied = 0

    def applied_index(self) -> int:
        return self._applied

    def apply(self, command: str, index: int = 0) -> Optional[Exception]:
        # Discard the payload, remember only how far the stream got
        # (volatile — a restart replays nothing because there is
        # nothing to rebuild).
        if index:
            self._applied = max(self._applied, index)
        return None

    def apply_batch(self, items) -> list:
        errs = []
        for _command, index in items:
            if index:
                self._applied = max(self._applied, index)
            errs.append(None)
        return errs

    def query(self, q: str) -> str:
        raise WitnessQueryError(
            "witness replica serves no reads (it owns no shard)")

    def close(self) -> None:
        pass
