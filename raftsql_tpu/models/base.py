"""State-machine protocol applied by committed log entries.

The reference hard-wires SQLite as its one state machine (reference
db.go:13-20); here apply/query are a protocol so multiple state-machine
families plug into the same replication engine: `sqlite_sm` (reference
parity) and `kv_sm` (dependency-free, used by benchmarks and chaos tests).

Snapshot/resume (beyond the reference, SURVEY.md §5.4): a state machine
MAY track the log index of the last applied entry durably and atomically
with the apply itself (`applied_index`).  The engine then resumes by
skipping re-apply of entries at or below it instead of deleting state and
replaying the full log (the reference's db.go:27-29 behavior, still the
default), and may compact the WAL prefix the snapshot covers.
"""
from __future__ import annotations

from typing import Optional, Protocol


class StateMachine(Protocol):
    def apply(self, command: str, index: int = 0) -> Optional[Exception]:
        """Execute a committed write command; returns the error, if any.
        Must be deterministic: every replica applies the same sequence.
        `index` is the entry's log position (1-based); snapshotting state
        machines persist it atomically with the command's effects."""
        ...

    def query(self, q: str) -> str:
        """Read-only local query; raises on invalid queries."""
        ...

    def applied_index(self) -> int:
        """Durable log index of the last applied entry; 0 if fresh or not
        tracked.  Only meaningful when the machine persists it atomically
        with apply (see SQLiteStateMachine resume mode).

        Machines whose applied_index survives a process crash advertise it
        with a truthy `has_durable_snapshot` attribute; the engine treats
        everything else as floor 0 for WAL compaction (compacting on a
        volatile index silently loses data on restart)."""
        ...

    def close(self) -> None: ...
