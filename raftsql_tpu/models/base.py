"""State-machine protocol applied by committed log entries.

The reference hard-wires SQLite as its one state machine (reference
db.go:13-20); here apply/query are a protocol so multiple state-machine
families plug into the same replication engine: `sqlite_sm` (reference
parity) and `kv_sm` (dependency-free, used by benchmarks and chaos tests).
"""
from __future__ import annotations

from typing import Optional, Protocol


class StateMachine(Protocol):
    def apply(self, command: str) -> Optional[Exception]:
        """Execute a committed write command; returns the error, if any.
        Must be deterministic: every replica applies the same sequence."""
        ...

    def query(self, q: str) -> str:
        """Read-only local query; raises on invalid queries."""
        ...

    def close(self) -> None: ...
