"""ctypes wrapper for the C++ KV apply plane (native/wal.cc kv_*).

The Python-resident durable path tops out on per-entry object handling:
every committed payload becomes a bytes object, a decoded str, a tuple,
and a dict op.  The native plane applies committed RANGES directly from
the native payload log — commands are parsed and applied inside one C
call per publish, and Python only moves [ranges]-shaped numpy columns.

Grammar parity with models/kv_sm.py KVStateMachine.apply ("SET <key>
<value>" / "DEL <key>", exactly-once via the per-group applied index) is
pinned by tests/test_native_kv.py, which races the two planes on the
same command stream.
"""
from __future__ import annotations

import ctypes
from typing import Optional, Tuple

import numpy as np


class NativeKV:
    has_durable_snapshot = False

    def __init__(self, num_groups: int, lib):
        """`lib` is the handle from native.build.load_native_plog()
        (the kv_* entry points share the WAL shared object)."""
        self._lib = lib
        self._h = lib.kv_new(num_groups)
        if not self._h:
            raise MemoryError("kv_new failed")
        self.num_groups = num_groups
        self.bad_commands = 0
        self.total_applied = 0    # sum of apply_plog return values

    def apply_plog(self, plog_handle, groups, starts, counts) -> int:
        """Apply entries [starts[r], starts[r]+counts[r]) of groups[r]
        read in place from the native payload log; returns the number
        applied (non-empty, not-yet-applied).  Bad commands accumulate
        in self.bad_commands (KV parity: per-entry error, batch goes
        on)."""
        n = len(groups)
        if n == 0:
            return 0
        ga = np.asarray(groups, np.uint32)
        sa = np.asarray(starts, np.uint64)
        ca = np.asarray(counts, np.uint32)
        bad = ctypes.c_uint64(0)
        done = self._lib.kv_apply_plog(
            self._h, plog_handle, n,
            ga.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            sa.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            ca.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            ctypes.byref(bad))
        self.bad_commands += bad.value
        if done == 0xFFFFFFFFFFFFFFFF:
            # Same fault and same contract as the Python publish path:
            # a committed index has no payload-log backing.  applied[]
            # reflects the pre-fault work, so nothing double-applies.
            raise RuntimeError("native KV: payload log shorter than "
                               "commit")
        self.total_applied += int(done)
        return int(done)

    def applied_index(self, group: int) -> int:
        return int(self._lib.kv_applied(self._h, group))

    def count(self, group: int) -> int:
        return int(self._lib.kv_count(self._h, group))

    def get(self, group: int, key: str) -> Optional[str]:
        kb = key.encode("utf-8")
        cap = 256
        while True:
            buf = (ctypes.c_uint8 * cap)()
            ln = self._lib.kv_get(self._h, group, kb, len(kb), buf, cap)
            if ln < 0:
                return None
            if ln <= cap:
                return bytes(buf[:ln]).decode("utf-8")
            cap = ln  # buffer was too small; retry at the exact size

    def query(self, group: int, q: str) -> str:
        """GET-<key> query parity for tests (KEYS is not exported by the
        C plane; replica comparison uses count() + spot gets)."""
        parts = q.split(" ", 1)
        if parts[0] == "GET" and len(parts) == 2:
            return self.get(group, parts[1]) or ""
        raise ValueError(f"bad query: {q!r}")

    def close(self) -> None:
        if self._h:
            self._lib.kv_free(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover - GC ordering
        try:
            self.close()
        except Exception:
            pass


def new_native_kv(num_groups: int) -> Optional[Tuple[NativeKV, object]]:
    """(NativeKV, lib) if the native plane is available, else None."""
    from raftsql_tpu.native.build import load_native_plog
    lib = load_native_plog()
    if lib is None:
        return None
    return NativeKV(num_groups, lib), lib
