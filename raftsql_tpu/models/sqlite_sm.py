"""SQLite state machine — reference-parity apply/query semantics.

Mirrors the reference's raftdb SQL handling (reference db.go):
  - the database file is DELETED on boot and rebuilt entirely from the
    replicated log — no snapshots yet (db.go:27-29);
  - writes are applied in commit order under a write lock (db.go:55-57);
  - reads run against the local replica only, never consulting the
    leader — stale reads are by design (db.go:128-130);
  - SELECT rows are rendered `|v1|v2|…|\n` with every column stringified
    via a byte-slice scan (db.go:137-156): NULL → empty cell, so the
    `||0|`-style strings the reference tests grep for fall out.

SQLite is C reached through CPython's `sqlite3` binding — the same
library the reference reaches through cgo (db.go:6), per SURVEY.md §2b V5.
"""
from __future__ import annotations

import os
import sqlite3
import threading
from typing import Optional


def is_select(query: str) -> bool:
    """First-token SELECT check, case-insensitive — the reference's naive
    write/read split (db.go:98-104), preserved deliberately."""
    tokens = query.strip(" ").split(" ")
    return len(tokens) > 0 and tokens[0].upper() == "SELECT"


def _cell(v) -> str:
    if v is None:
        return ""
    if isinstance(v, bytes):
        return v.decode("utf-8", "replace")
    if isinstance(v, float):
        return repr(v)
    return str(v)


class SQLiteStateMachine:
    """`resume=False` (default): reference parity — the DB file is deleted
    on boot and rebuilt from the log (db.go:29).

    `resume=True`: the DB file IS the snapshot.  Every apply writes the
    entry's log index into the `_raft_meta` table inside the SAME SQLite
    transaction as the command, so file-state and applied-index are
    crash-atomic; on reboot the engine skips entries at or below
    `applied_index()` instead of replaying from scratch."""

    def __init__(self, path: str, resume: bool = False):
        if not resume and path != ":memory:" and os.path.exists(path):
            os.remove(path)
        self.path = path
        self.resume = resume
        # WAL compaction may only trust applied_index() as a floor when it
        # survives a crash (models/base.py contract).
        self.has_durable_snapshot = resume and path != ":memory:"
        self._conn = self._connect()
        self._lock = threading.Lock()
        self._applied = 0
        if resume:
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS _raft_meta "
                "(k TEXT PRIMARY KEY, v INTEGER)")
            self._conn.commit()
            row = self._conn.execute(
                "SELECT v FROM _raft_meta WHERE k='applied_index'"
            ).fetchone()
            self._applied = int(row[0]) if row else 0

    def _connect(self) -> sqlite3.Connection:
        """Open self.path configured for this state machine: manual
        transaction control (apply_batch brackets its own BEGIN/COMMIT
        group commit — the module's implicit-BEGIN machinery would fight
        the explicit statements) and journaling matched to the upstream
        durability model.  Durability belongs to the raft WAL, not
        SQLite:
          - parity mode deletes and rebuilds this file from the log on
            every boot (db.go:27-29), so per-statement fsync buys
            nothing — memory journal, no syncs;
          - resume mode needs (commands, applied_index) ATOMIC, not
            durable-per-statement: SQLite-WAL + synchronous=NORMAL can
            lose a recent tail on power loss but always rolls the file
            back to a consistent point whose applied_index matches, and
            the raft log replays forward from there — exactly-once
            preserved at a fraction of the fsync cost."""
        conn = sqlite3.connect(self.path, check_same_thread=False)
        conn.isolation_level = None
        try:
            if self.has_durable_snapshot:
                conn.execute("PRAGMA journal_mode=WAL")
                conn.execute("PRAGMA synchronous=NORMAL")
            else:
                conn.execute("PRAGMA journal_mode=MEMORY")
                conn.execute("PRAGMA synchronous=OFF")
        except sqlite3.Error:          # pragma: no cover - pragma support
            pass
        return conn

    def applied_index(self) -> int:
        return self._applied

    def apply(self, command: str, index: int = 0) -> Optional[Exception]:
        return self.apply_batch([(command, index)])[0]

    def apply_batch(self, items) -> list:
        """Apply `[(command, index), ...]` in ONE durable transaction
        (group commit): per-statement outcomes are isolated with
        SAVEPOINTs, and the batch's statements plus the final
        applied_index land atomically — so a crash re-delivers the whole
        batch (exactly-once via the applied floor), never half of it.
        Returns one Optional[Exception] per item.

        The exactly-once check lives under the SAME lock install()
        takes: a snapshot install racing the applier thread bumps
        _applied before this runs, so a stale queued entry can never
        re-apply over the installed image."""
        with self._lock:
            errs: list = []
            attempted: list = []     # False = skipped as already applied
            last = 0
            try:
                self._conn.execute("BEGIN")
            except sqlite3.Error:       # already in a transaction
                pass
            for command, index in items:
                if self.resume and index and index <= self._applied:
                    errs.append(None)
                    attempted.append(False)
                    continue
                attempted.append(True)
                try:
                    self._conn.execute("SAVEPOINT _apply")
                    self._conn.execute(command)
                    self._conn.execute("RELEASE _apply")
                    errs.append(None)
                except sqlite3.Error as e:
                    # A failed command still consumes its entry (the
                    # error is its outcome, reference db.go:55-80): undo
                    # only ITS effects, keep the batch.
                    try:
                        self._conn.execute("ROLLBACK TO _apply")
                        self._conn.execute("RELEASE _apply")
                    except sqlite3.Error:
                        pass
                    errs.append(e)
                if index:
                    last = max(last, index)
            meta = ("INSERT INTO _raft_meta (k, v) VALUES "
                    "('applied_index', ?) ON CONFLICT(k) DO UPDATE "
                    "SET v=excluded.v")
            try:
                if self.resume and last:
                    self._conn.execute(meta, (last,))
                self._conn.commit()
                if last:
                    self._applied = last
            except sqlite3.Error as e:
                # Commit failure (disk full): nothing of the batch
                # landed.  Report it on every entry attempted in THIS
                # transaction (skipped duplicates keep their None — they
                # are durable from an earlier boot), then try to advance
                # the durable floor alone so the entries stay consumed
                # ("the error is their outcome") — the applied floor may
                # only move when it is durable, because WAL compaction
                # and snapshot labeling trust it (models/base.py).
                try:
                    self._conn.rollback()
                except sqlite3.Error:
                    pass
                errs = [err if (err is not None or not att) else e
                        for err, att in zip(errs, attempted)]
                if last:
                    try:
                        if self.resume:
                            self._conn.execute(meta, (last,))
                            self._conn.commit()
                        self._applied = last
                    except sqlite3.Error:
                        pass            # floor stays; log re-delivers
            return errs

    def _image(self) -> bytes:
        """Serialize in DELETE journal mode: a WAL-mode image cannot be
        `deserialize`d by a receiver (in-memory databases reject WAL),
        and an image header should not advertise a -wal sidecar it does
        not carry.  Caller holds the lock; the mode flip checkpoints,
        which is fine at InstallSnapshot cadence.

        `Connection.serialize` only exists on Python 3.11+; older
        interpreters fall back to `VACUUM INTO` a temp file (SQLite ≥
        3.27) — the vacuum output is always a standalone DELETE-mode
        image, so no journal flip is needed on that path."""
        if not hasattr(self._conn, "serialize"):
            return self._vacuum_image()
        wal = self.has_durable_snapshot
        if wal:
            self._conn.execute("PRAGMA journal_mode=DELETE")
        try:
            return self._conn.serialize()
        finally:
            if wal:
                self._conn.execute("PRAGMA journal_mode=WAL")

    def _vacuum_image(self) -> bytes:
        """Point-in-time image via `VACUUM INTO` (the py3.10 fallback
        for Connection.serialize): SQLite writes a consistent, compacted
        copy of the whole database to a fresh file inside one internal
        read transaction — the same snapshot guarantee serialize gives.
        Caller holds the lock."""
        import tempfile
        d = tempfile.mkdtemp(prefix="raftsql-snap-")
        target = os.path.join(d, "image.db")   # must not pre-exist
        try:
            self._conn.execute("VACUUM INTO ?", (target,))
            with open(target, "rb") as f:
                return f.read()
        finally:
            import shutil
            shutil.rmtree(d, ignore_errors=True)

    def serialize(self) -> bytes:
        """Consistent point-in-time image of the database (the blob of an
        InstallSnapshot transfer)."""
        with self._lock:
            return self._image()

    def serialize_with_index(self):
        """(applied_index, image) captured atomically — the pair an
        InstallSnapshot sender needs (an apply sneaking between the two
        reads would mislabel the image's log position)."""
        with self._lock:
            return self._applied, self._image()

    def install(self, blob: bytes, index: int) -> None:
        """Replace all state with a serialized image applied up to
        `index` (receiver side of InstallSnapshot).

        With a real file, the image replaces the FILE (atomic tmp +
        rename, stale -wal/-shm sidecars dropped) and the connection
        reopens on it — `deserialize` would silently detach the
        connection onto an in-memory copy, so post-install applies
        never reached disk and a restart resurrected the pre-install
        file.  The in-memory path keeps deserialize."""
        with self._lock:
            if self.path != ":memory:":
                # Image lands in a tmp file BEFORE the live connection
                # closes: if the write fails (ENOSPC), the pre-install
                # state machine stays fully usable and the node just
                # drops the transfer.
                tmp = self.path + ".snap"
                with open(tmp, "wb") as f:
                    f.write(blob)
                    f.flush()
                    os.fsync(f.fileno())
                self._conn.close()
                try:
                    os.replace(tmp, self.path)
                    for suffix in ("-wal", "-shm"):
                        try:
                            os.remove(self.path + suffix)
                        except OSError:
                            pass
                finally:
                    self._conn = self._connect()
            elif hasattr(self._conn, "deserialize"):
                self._conn.deserialize(blob)
            else:
                # py3.10 fallback (Connection.deserialize is 3.11+):
                # land the image in a temp file and copy it over the
                # live in-memory database with Connection.backup, which
                # replaces the destination's entire content — the same
                # all-state-swap contract deserialize gives.
                import tempfile
                d = tempfile.mkdtemp(prefix="raftsql-snap-")
                tmp2 = os.path.join(d, "image.db")
                try:
                    with open(tmp2, "wb") as f:
                        f.write(blob)
                    src = sqlite3.connect(tmp2)
                    try:
                        src.backup(self._conn)
                    finally:
                        src.close()
                finally:
                    import shutil
                    shutil.rmtree(d, ignore_errors=True)
            if self.resume:
                self._conn.execute(
                    "CREATE TABLE IF NOT EXISTS _raft_meta "
                    "(k TEXT PRIMARY KEY, v INTEGER)")
                self._conn.execute(
                    "INSERT INTO _raft_meta (k, v) VALUES "
                    "('applied_index', ?) ON CONFLICT(k) DO UPDATE "
                    "SET v=excluded.v", (index,))
                self._conn.commit()
            self._applied = index

    def query(self, q: str) -> str:
        with self._lock:
            cur = self._conn.execute(q)
            rows = cur.fetchall()
        out = []
        for row in rows:
            out.append("|" + "|".join(_cell(v) for v in row) + "|\n")
        return "".join(out)

    def rows(self, q: str) -> list:
        """Structured read: the raw result tuples.  The reshard plane
        moves row values between groups verbatim, so it cannot use
        query()'s pipe-delimited rendering (a value containing '|'
        would be torn on re-parse)."""
        with self._lock:
            cur = self._conn.execute(q)
            return cur.fetchall()

    def close(self) -> None:
        with self._lock:
            self._conn.close()
