"""SQLite state machine — reference-parity apply/query semantics.

Mirrors the reference's raftdb SQL handling (reference db.go):
  - the database file is DELETED on boot and rebuilt entirely from the
    replicated log — no snapshots yet (db.go:27-29);
  - writes are applied in commit order under a write lock (db.go:55-57);
  - reads run against the local replica only, never consulting the
    leader — stale reads are by design (db.go:128-130);
  - SELECT rows are rendered `|v1|v2|…|\n` with every column stringified
    via a byte-slice scan (db.go:137-156): NULL → empty cell, so the
    `||0|`-style strings the reference tests grep for fall out.

SQLite is C reached through CPython's `sqlite3` binding — the same
library the reference reaches through cgo (db.go:6), per SURVEY.md §2b V5.
"""
from __future__ import annotations

import os
import sqlite3
import threading
from typing import Optional


def is_select(query: str) -> bool:
    """First-token SELECT check, case-insensitive — the reference's naive
    write/read split (db.go:98-104), preserved deliberately."""
    tokens = query.strip(" ").split(" ")
    return len(tokens) > 0 and tokens[0].upper() == "SELECT"


def _cell(v) -> str:
    if v is None:
        return ""
    if isinstance(v, bytes):
        return v.decode("utf-8", "replace")
    if isinstance(v, float):
        return repr(v)
    return str(v)


class SQLiteStateMachine:
    def __init__(self, path: str):
        # Rebuilt from the log on every boot (reference db.go:29).
        if path != ":memory:" and os.path.exists(path):
            os.remove(path)
        self.path = path
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()

    def apply(self, command: str) -> Optional[Exception]:
        with self._lock:
            try:
                self._conn.execute(command)
                self._conn.commit()
                return None
            except sqlite3.Error as e:
                return e

    def query(self, q: str) -> str:
        with self._lock:
            cur = self._conn.execute(q)
            rows = cur.fetchall()
        out = []
        for row in rows:
            out.append("|" + "|".join(_cell(v) for v in row) + "|\n")
        return "".join(out)

    def close(self) -> None:
        with self._lock:
            self._conn.close()
