"""SQLite state machine — reference-parity apply/query semantics.

Mirrors the reference's raftdb SQL handling (reference db.go):
  - the database file is DELETED on boot and rebuilt entirely from the
    replicated log — no snapshots yet (db.go:27-29);
  - writes are applied in commit order under a write lock (db.go:55-57);
  - reads run against the local replica only, never consulting the
    leader — stale reads are by design (db.go:128-130);
  - SELECT rows are rendered `|v1|v2|…|\n` with every column stringified
    via a byte-slice scan (db.go:137-156): NULL → empty cell, so the
    `||0|`-style strings the reference tests grep for fall out.

SQLite is C reached through CPython's `sqlite3` binding — the same
library the reference reaches through cgo (db.go:6), per SURVEY.md §2b V5.
"""
from __future__ import annotations

import os
import sqlite3
import threading
from typing import Optional


def is_select(query: str) -> bool:
    """First-token SELECT check, case-insensitive — the reference's naive
    write/read split (db.go:98-104), preserved deliberately."""
    tokens = query.strip(" ").split(" ")
    return len(tokens) > 0 and tokens[0].upper() == "SELECT"


def _cell(v) -> str:
    if v is None:
        return ""
    if isinstance(v, bytes):
        return v.decode("utf-8", "replace")
    if isinstance(v, float):
        return repr(v)
    return str(v)


class SQLiteStateMachine:
    """`resume=False` (default): reference parity — the DB file is deleted
    on boot and rebuilt from the log (db.go:29).

    `resume=True`: the DB file IS the snapshot.  Every apply writes the
    entry's log index into the `_raft_meta` table inside the SAME SQLite
    transaction as the command, so file-state and applied-index are
    crash-atomic; on reboot the engine skips entries at or below
    `applied_index()` instead of replaying from scratch."""

    def __init__(self, path: str, resume: bool = False):
        if not resume and path != ":memory:" and os.path.exists(path):
            os.remove(path)
        self.path = path
        self.resume = resume
        # WAL compaction may only trust applied_index() as a floor when it
        # survives a crash (models/base.py contract).
        self.has_durable_snapshot = resume and path != ":memory:"
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        self._applied = 0
        if resume:
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS _raft_meta "
                "(k TEXT PRIMARY KEY, v INTEGER)")
            self._conn.commit()
            row = self._conn.execute(
                "SELECT v FROM _raft_meta WHERE k='applied_index'"
            ).fetchone()
            self._applied = int(row[0]) if row else 0

    def applied_index(self) -> int:
        return self._applied

    def apply(self, command: str, index: int = 0) -> Optional[Exception]:
        with self._lock:
            # The authoritative exactly-once check lives under the SAME
            # lock install() takes: a snapshot install racing the applier
            # thread bumps _applied before this runs, so a stale queued
            # entry can never re-apply over the installed image.
            if self.resume and index and index <= self._applied:
                return None
            try:
                self._conn.execute(command)
                if self.resume and index:
                    # Same transaction as the command: crash-atomic
                    # exactly-once apply.
                    self._conn.execute(
                        "INSERT INTO _raft_meta (k, v) VALUES "
                        "('applied_index', ?) ON CONFLICT(k) DO UPDATE "
                        "SET v=excluded.v", (index,))
                self._conn.commit()
                if index:
                    self._applied = index
                return None
            except sqlite3.Error as e:
                # A failed command still advances the applied index (the
                # entry was consumed, its error is its outcome) — roll
                # back its effects, then record the index alone.  The
                # recovery writes get their own guard: if they too fail
                # (disk full), the ORIGINAL error must still be returned
                # rather than escaping and killing the applier thread.
                try:
                    self._conn.rollback()
                    if self.resume and index:
                        self._conn.execute(
                            "INSERT INTO _raft_meta (k, v) VALUES "
                            "('applied_index', ?) ON CONFLICT(k) DO "
                            "UPDATE SET v=excluded.v", (index,))
                        self._conn.commit()
                    if index:
                        self._applied = index
                except sqlite3.Error:
                    pass
                return e

    def serialize(self) -> bytes:
        """Consistent point-in-time image of the database (the blob of an
        InstallSnapshot transfer)."""
        with self._lock:
            return self._conn.serialize()

    def serialize_with_index(self):
        """(applied_index, image) captured atomically — the pair an
        InstallSnapshot sender needs (an apply sneaking between the two
        reads would mislabel the image's log position)."""
        with self._lock:
            return self._applied, self._conn.serialize()

    def install(self, blob: bytes, index: int) -> None:
        """Replace all state with a serialized image applied up to
        `index` (receiver side of InstallSnapshot)."""
        with self._lock:
            self._conn.deserialize(blob)
            if self.resume:
                self._conn.execute(
                    "CREATE TABLE IF NOT EXISTS _raft_meta "
                    "(k TEXT PRIMARY KEY, v INTEGER)")
                self._conn.execute(
                    "INSERT INTO _raft_meta (k, v) VALUES "
                    "('applied_index', ?) ON CONFLICT(k) DO UPDATE "
                    "SET v=excluded.v", (index,))
                self._conn.commit()
            self._applied = index

    def query(self, q: str) -> str:
        with self._lock:
            cur = self._conn.execute(q)
            rows = cur.fetchall()
        out = []
        for row in rows:
            out.append("|" + "|".join(_cell(v) for v in row) + "|\n")
        return "".join(out)

    def close(self) -> None:
        with self._lock:
            self._conn.close()
