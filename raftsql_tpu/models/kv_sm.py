"""In-memory key-value state machine.

A second state-machine family behind models.base.StateMachine: no SQLite,
no disk — used by benchmarks (apply cost ≈ 0 isolates consensus
throughput) and by chaos tests that compare replica states directly.

Commands:  ``SET <key> <value>`` / ``DEL <key>``
Queries:   ``GET <key>`` → value or empty; ``KEYS`` → sorted keys.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional


class KVStateMachine:
    # _applied is volatile: a restart loses it, so it must never be used
    # as a WAL-compaction floor (runtime/db.py gates on this flag).
    has_durable_snapshot = False

    def __init__(self, path: str = ""):
        self._data: Dict[str, str] = {}
        self._lock = threading.Lock()
        self._applied = 0   # volatile — KV has no durable snapshot

    def applied_index(self) -> int:
        return self._applied

    def apply(self, command: str, index: int = 0) -> Optional[Exception]:
        parts = command.split(" ", 2)
        with self._lock:
            if index and index <= self._applied:
                return None     # already covered (e.g. by an install)
            try:
                if parts[0] == "SET" and len(parts) == 3:
                    self._data[parts[1]] = parts[2]
                elif parts[0] == "DEL" and len(parts) == 2:
                    self._data.pop(parts[1], None)
                else:
                    return ValueError(f"bad command: {command!r}")
                return None
            except Exception as e:     # pragma: no cover - defensive
                return e
            finally:
                if index:
                    self._applied = index

    def apply_batch(self, items) -> list:
        """Batched apply: one lock hold for [(command, index), ...] in
        commit order — the apply layer's group-commit path (runtime/db.py
        _apply_run prefers this; per-item apply() paid a lock round trip
        per entry at durable-bench saturation)."""
        errs = []
        with self._lock:
            data = self._data
            applied = self._applied
            for command, index in items:
                if index and index <= applied:
                    errs.append(None)
                    continue
                parts = command.split(" ", 2)
                if parts[0] == "SET" and len(parts) == 3:
                    data[parts[1]] = parts[2]
                    errs.append(None)
                elif parts[0] == "DEL" and len(parts) == 2:
                    data.pop(parts[1], None)
                    errs.append(None)
                else:
                    errs.append(ValueError(f"bad command: {command!r}"))
                if index:
                    applied = index
            self._applied = applied
        return errs

    def query(self, q: str) -> str:
        parts = q.split(" ", 1)
        with self._lock:
            if parts[0] == "GET" and len(parts) == 2:
                return self._data.get(parts[1], "")
            if parts[0] == "KEYS":
                return "\n".join(sorted(self._data))
        raise ValueError(f"bad query: {q!r}")

    def snapshot(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._data)

    def serialize(self) -> bytes:
        import json
        with self._lock:
            return json.dumps(self._data).encode()

    def serialize_with_index(self):
        import json
        with self._lock:
            return self._applied, json.dumps(self._data).encode()

    def install(self, blob: bytes, index: int) -> None:
        import json
        with self._lock:
            self._data = json.loads(blob.decode())
            self._applied = index

    def close(self) -> None:
        pass
