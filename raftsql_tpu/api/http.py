"""HTTP client API — reference-parity PUT/GET semantics.

Mirrors the reference's httpSQLAPI (reference httpapi.go:26-79):
  - PUT: body is a write SQL statement; proposed through consensus; the
    response blocks until the statement is committed AND applied locally.
    204 No Content on success, 400 + error text on failure
    (httpapi.go:38-49).
  - GET: body is a SELECT; served from the local replica, no consensus;
    rows rendered `|v1|v2|…|\n` (httpapi.go:51-62).
  - anything else: 405 with `Allow: PUT, GET` (httpapi.go:63-66).

Extensions beyond the reference (multi-group engine):
  - `X-Raft-Group` header selects the raft group (default 0);
  - `X-Consistency: linear` on GET upgrades the read to LINEARIZABLE
    (ReadIndex, raft §6.4): served only by the group's leader after a
    quorum re-confirms its leadership and the local apply catches up to
    the read point; non-leaders answer 421 + `X-Raft-Leader` so the
    client can retry at the leader.  Plain GETs stay reference-parity
    stale local reads;
  - `GET /metrics` returns node counters as JSON (SURVEY.md §5.5).
"""
from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from raftsql_tpu.runtime.db import NotLeaderError, RaftDB

log = logging.getLogger("raftsql_tpu.http")


def _session_headers(rdb, group: int) -> Optional[dict]:
    """X-Raft-Session commit-watermark echo (session reads / read-your-
    writes).  A watermark is advisory — never fail a served request
    over a failed gauge read."""
    try:
        return {"X-Raft-Session": str(rdb.watermark(group))}
    except Exception:                                   # noqa: BLE001
        return None


def _make_handler(rdb: RaftDB, timeout_s: float):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):   # quiet, like the reference
            pass

        def _body(self) -> str:
            n = int(self.headers.get("Content-Length") or 0)
            return self.rfile.read(n).decode("utf-8")

        def _group(self) -> int:
            return int(self.headers.get("X-Raft-Group") or 0)

        def _send(self, code: int, body: bytes = b"",
                  ctype: str = "text/plain; charset=utf-8",
                  headers: Optional[dict] = None) -> None:
            self.send_response(code)
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            if body or code != 204:
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            if body:
                self.wfile.write(body)

        def _err(self, e: Exception) -> None:
            # dumpErr (reference httpapi.go:30-34): log + 400 + text.
            msg = str(e)
            log.info("client error: %s", msg)
            self._send(400, (msg + "\n").encode("utf-8"))

        def _retry_token(self) -> Optional[int]:
            """X-Raft-Retry-Token: hex u64 pinning the proposal's
            envelope id so a client-side re-send applies exactly once
            (api/client.py sets one per logical PUT)."""
            tok = self.headers.get("X-Raft-Retry-Token")
            if tok is None:
                return None
            return int(tok, 16) & ((1 << 64) - 1)

        def do_PUT(self):
            try:
                query, group = self._body(), self._group()
                fut = rdb.propose(query, group, token=self._retry_token())
                try:
                    err = fut.wait(timeout_s)
                except TimeoutError:
                    # Deregister the ack so it cannot leak (the statement
                    # may still commit later; only this client gave up).
                    rdb.abandon(query, group, fut)
                    raise
            except Exception as e:
                self._err(e)
                return
            if err is not None:
                self._err(err)
            else:
                # The ack implies local apply: the watermark echoed
                # here covers this very write (X-Raft-Session —
                # present it on a session read for read-your-writes).
                self._send(204, headers=_session_headers(rdb, group))

        def do_GET(self):
            if self.path == "/healthz":
                # Readiness: id, per-group role/leader/term/applied.
                # Answering at all proves boot + replay completed (the
                # nemesis's restart-detection probe, no write needed).
                self._body()    # drain — keep-alive
                self._send(200, rdb.render_health().encode(),
                           ctype="application/json")
                return
            if self.path.partition("?")[0] == "/metrics":
                # Content negotiation (utils/metrics.py wants_prom):
                # ?format=prom or a Prometheus/OpenMetrics Accept
                # header gets the text exposition; default stays JSON.
                from raftsql_tpu.utils.metrics import (PROM_CONTENT_TYPE,
                                                       wants_prom)
                self._body()    # drain — a leftover body corrupts keep-alive
                if wants_prom(self.path.partition("?")[2],
                              self.headers.get("Accept", "")):
                    self._send(200, rdb.render_metrics_prom().encode(),
                               ctype=PROM_CONTENT_TYPE)
                else:
                    self._send(200, rdb.render_metrics().encode(),
                               ctype="application/json")
                return
            if self.path == "/trace":
                # Chrome trace-event JSON (Perfetto-loadable): the span
                # tracer + device event ring (raftsql_tpu/obs/).  Valid
                # empty document while tracing is off (the default).
                self._body()    # drain — keep-alive
                self._send(200, rdb.render_trace().encode(),
                           ctype="application/json")
                return
            if self.path == "/events":
                self._body()    # drain — keep-alive
                self._send(200, rdb.render_events().encode(),
                           ctype="application/json")
                return
            if self.path == "/members":
                # Membership admin read (raftsql_tpu/membership/):
                # per-group active config, joint state, leader hint.
                self._body()    # drain — keep-alive
                self._send(200, rdb.render_members().encode(),
                           ctype="application/json")
                return
            try:
                # X-Consistency selects the read mode (README
                # read-modes table): local (default) / session /
                # follower / linear.  X-Raft-Session carries the
                # session watermark (the commit-watermark echo a
                # previous response returned).
                mode = (self.headers.get("X-Consistency", "")
                        .lower() or "local")
                wm = int(self.headers.get("X-Raft-Session") or 0)
                group = self._group()
                rows = rdb.query(self._body(), group, timeout=timeout_s,
                                 mode=mode, watermark=wm)
            except NotLeaderError as e:
                # 421 Misdirected Request + the leader hint: the client
                # retries its linearizable read against that node.
                self._send(421, (str(e) + "\n").encode("utf-8"),
                           headers={"X-Raft-Leader": str(e.leader)}
                           if e.leader > 0 else None)
                return
            except TimeoutError as e:
                # Transient server-side condition (quorum unreachable or
                # apply lagging) — retryable, NOT a client error.
                self._send(503, (str(e) + "\n").encode("utf-8"))
                return
            except Exception as e:
                self._err(e)
                return
            # Commit-watermark echo: the client's next session read
            # presents this to get read-your-writes anywhere.
            self._send(200, rows.encode("utf-8"),
                       headers=_session_headers(rdb, group))

        def _method_not_allowed(self):
            self._body()    # drain — a leftover body corrupts keep-alive
            self.send_response(405)
            self.send_header("Allow", "PUT, GET")
            body = b"Method not allowed\n"
            # HEAD responses must carry no body (a written body would be
            # parsed as the next response on a keep-alive connection).
            if self.command == "HEAD":
                body = b""
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            if body:
                self.wfile.write(body)

        def do_POST(self):
            # Admin writes: POST /members
            # {"group": 0, "op": "add|add_learner|promote|remove|
            #  remove_learner", "peer": <slot>} and POST /transfer
            # {"group": 0, "target": <slot>} (graceful leadership
            # transfer, thesis §3.10).  Leader-only: elsewhere answers
            # 421 + X-Raft-Leader like linearizable reads.
            if self.path not in ("/members", "/transfer"):
                self._method_not_allowed()
                return
            try:
                req = json.loads(self._body() or "{}")
                if self.path == "/transfer":
                    got = rdb.transfer(int(req.get("group", 0)),
                                       int(req.get("target", -1)))
                else:
                    got = rdb.member_change(int(req.get("group", 0)),
                                            str(req.get("op", "")),
                                            int(req.get("peer", -1)))
            except NotLeaderError as e:
                self._send(421, (str(e) + "\n").encode("utf-8"),
                           headers={"X-Raft-Leader": str(e.leader)}
                           if e.leader > 0 else None)
                return
            except Exception as e:
                self._err(e)
                return
            self._send(200, (json.dumps(got, sort_keys=True)
                             + "\n").encode(),
                       ctype="application/json")

        do_DELETE = _method_not_allowed
        do_PATCH = _method_not_allowed
        do_HEAD = _method_not_allowed

    return Handler


class _Server(ThreadingHTTPServer):
    # The stdlib default listen backlog of 5 resets connections when a
    # burst of concurrent keep-alive clients arrives; scoped here so no
    # other ThreadingHTTPServer in the process is affected.
    request_queue_size = 256


class SQLServer:
    """Stoppable HTTP server (the reference's stoppable listener pattern,
    listener.go:25-59, applied to the client API)."""

    def __init__(self, port: int, rdb: RaftDB, host: str = "",
                 timeout_s: float = 30.0):
        self.httpd = _Server((host, port), _make_handler(rdb, timeout_s))
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def serve_forever(self) -> None:
        self.httpd.serve_forever()

    def start(self) -> None:
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True, name="sql-http")
        self._thread.start()

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)


def serve_http_sql_api(port: int, rdb: RaftDB) -> None:
    """Blocking entry point, mirroring ServeHttpSqlAPI
    (reference httpapi.go:71-79)."""
    SQLServer(port, rdb).serve_forever()
