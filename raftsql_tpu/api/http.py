"""HTTP client API — reference-parity PUT/GET semantics.

Mirrors the reference's httpSQLAPI (reference httpapi.go:26-79):
  - PUT: body is a write SQL statement; proposed through consensus; the
    response blocks until the statement is committed AND applied locally.
    204 No Content on success, 400 + error text on failure
    (httpapi.go:38-49).
  - GET: body is a SELECT; served from the local replica, no consensus;
    rows rendered `|v1|v2|…|\n` (httpapi.go:51-62).
  - anything else: 405 with `Allow: PUT, GET` (httpapi.go:63-66).

Extensions beyond the reference (multi-group engine):
  - `X-Raft-Group` header selects the raft group (default 0);
  - `X-Consistency: linear` on GET upgrades the read to LINEARIZABLE
    (ReadIndex, raft §6.4): served only by the group's leader after a
    quorum re-confirms its leadership and the local apply catches up to
    the read point; non-leaders answer 421 + `X-Raft-Leader` so the
    client can retry at the leader.  Plain GETs stay reference-parity
    stale local reads;
  - `GET /metrics` returns node counters as JSON (SURVEY.md §5.5).
"""
from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from raftsql_tpu.overload import (Overloaded, retry_after_header,
                                  retryable_refusal)
from raftsql_tpu.runtime.db import NotLeaderError, RaftDB

log = logging.getLogger("raftsql_tpu.http")


def _session_headers(rdb, group: int) -> Optional[dict]:
    """X-Raft-Session commit-watermark echo (session reads / read-your-
    writes).  A watermark is advisory — never fail a served request
    over a failed gauge read."""
    try:
        return {"X-Raft-Session": str(rdb.watermark(group))}
    except Exception:                                   # noqa: BLE001
        return None


def _make_handler(rdb: RaftDB, timeout_s: float):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):   # quiet, like the reference
            pass

        def _body(self) -> str:
            n = int(self.headers.get("Content-Length") or 0)
            return self.rfile.read(n).decode("utf-8")

        def _group(self) -> int:
            return int(self.headers.get("X-Raft-Group") or 0)

        def _send(self, code: int, body: bytes = b"",
                  ctype: str = "text/plain; charset=utf-8",
                  headers: Optional[dict] = None) -> None:
            self.send_response(code)
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            if body or code != 204:
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            if body:
                self.wfile.write(body)

        def _err(self, e: Exception) -> None:
            # dumpErr (reference httpapi.go:30-34): log + 400 + text.
            msg = str(e)
            log.info("client error: %s", msg)
            self._send(400, (msg + "\n").encode("utf-8"))

        def _refuse(self, e: Exception) -> None:
            """THE retryable-refusal path for this plane: `Overloaded`
            becomes 429 with the controller's jittered drain-rate
            Retry-After, every other transient condition becomes 503
            with its default — both ALWAYS carry Retry-After, so
            api/client.py can hold off per-node instead of hammering
            the rotation (the aio plane emits the identical contract
            via the same overload helpers)."""
            code, retry_s = retryable_refusal(e)
            self._send(code, (str(e) + "\n").encode("utf-8"),
                       headers={"Retry-After":
                                retry_after_header(retry_s)})

        def _deadline_ms(self) -> Optional[float]:
            """X-Raft-Deadline-Ms: the client's REMAINING end-to-end
            budget for this attempt, in milliseconds."""
            d = self.headers.get("X-Raft-Deadline-Ms")
            return float(d) if d is not None else None

        def _shed_expired(self, deadline_ms: Optional[float]) -> bool:
            """Edge shed: a request whose budget is already spent does
            no consensus work at all — 504, counted shed_edge.
            Returns True when the request was answered here."""
            if deadline_ms is None or deadline_ms > 0:
                return False
            ov = getattr(rdb.pipe.node, "overload", None)
            if ov is not None:
                ov.note_shed("edge")
            self._send(504, b"deadline exceeded (edge)\n")
            return True

        def _brownout_ok(self) -> bool:
            """X-Raft-Brownout: allow — the client consents to a
            session-read downgrade when the brownout ladder engages."""
            return (self.headers.get("X-Raft-Brownout", "")
                    .strip().lower() == "allow")

        def _retry_token(self) -> Optional[int]:
            """X-Raft-Retry-Token: hex u64 pinning the proposal's
            envelope id so a client-side re-send applies exactly once
            (api/client.py sets one per logical PUT)."""
            tok = self.headers.get("X-Raft-Retry-Token")
            if tok is None:
                return None
            return int(tok, 16) & ((1 << 64) - 1)

        def _epoch_hdr(self) -> Optional[int]:
            """X-Raft-Keymap-Epoch: the mapping version the client
            routed by.  The reshard plane fails closed on any
            mismatch (api/client.py refreshes from /healthz)."""
            e = self.headers.get("X-Raft-Keymap-Epoch")
            return int(e) if e is not None else None

        def _kv_refused(self, e: Exception) -> bool:
            """Map reshard routing refusals onto responses.  Returns
            True when the request was answered here."""
            from raftsql_tpu.reshard.plane import FrozenSlot, WrongEpoch
            if isinstance(e, WrongEpoch):
                # 409 + the CURRENT keymap document: the client swaps
                # its cached mapping and re-routes — never served with
                # a mapping the router may have moved under it.
                body = json.dumps(
                    {"error": str(e),
                     "keymap": rdb.reshard.keymap.to_doc()},
                    sort_keys=True) + "\n"
                self._send(409, body.encode(),
                           ctype="application/json",
                           headers={"X-Raft-Keymap-Epoch":
                                    str(e.have)})
                return True
            if isinstance(e, FrozenSlot):
                # Retryable: the verb resolves and unfreezes the slot.
                self._refuse(e)
                return True
            return False

        def _do_kv(self, key: str):
            """Keyed surface over the elastic keyspace: the reshard
            plane routes by hash slot, the response pins the mapping
            epoch it served under."""
            if rdb.reshard is None:
                self._body()    # drain — keep-alive
                self._send(503, b"no reshard plane (--reshard)\n")
                return
            plane = rdb.reshard
            served: dict = {}
            try:
                dl = self._deadline_ms()
                if self.command == "PUT":
                    group, sql = plane.kv_put(key, self._body(),
                                              self._epoch_hdr())
                    if self._shed_expired(dl):
                        return
                    fut = rdb.propose(sql, group,
                                      token=self._retry_token(),
                                      **({} if dl is None
                                         else {"deadline_ms": dl}))
                    try:
                        err = fut.wait(timeout_s if dl is None
                                       else min(timeout_s, dl / 1000.0))
                    except TimeoutError:
                        rdb.abandon(sql, group, fut)
                        raise
                    if err is not None:
                        raise err
                    hdrs = _session_headers(rdb, group) or {}
                    hdrs["X-Raft-Keymap-Epoch"] = str(plane.keymap.epoch)
                    self._send(204, headers=hdrs)
                    return
                group, sql = plane.kv_get(key, self._epoch_hdr())
                mode = (self.headers.get("X-Consistency", "")
                        .lower() or "local")
                wm = int(self.headers.get("X-Raft-Session") or 0)
                self._body()    # drain — keep-alive
                if self._shed_expired(dl):
                    return
                rows = rdb.query(sql, group, timeout=timeout_s,
                                 mode=mode, watermark=wm,
                                 deadline_ms=dl,
                                 brownout=self._brownout_ok(),
                                 info=served)
            except Overloaded as e:
                self._refuse(e)
                return
            except NotLeaderError as e:
                self._send(421, (str(e) + "\n").encode("utf-8"),
                           headers={"X-Raft-Leader": str(e.leader)}
                           if e.leader > 0 else None)
                return
            except TimeoutError as e:
                self._refuse(e)
                return
            except Exception as e:
                if not self._kv_refused(e):
                    self._err(e)
                return
            hdrs = _session_headers(rdb, group) or {}
            hdrs["X-Raft-Keymap-Epoch"] = str(plane.keymap.epoch)
            if served.get("served"):
                hdrs["X-Raft-Served-Mode"] = served["served"]
            val = plane.kv_value(rows)
            if val is None:
                self._send(404, b"", headers=hdrs)
            else:
                self._send(200, val.encode("utf-8"), headers=hdrs)

        def do_PUT(self):
            if self.path.startswith("/kv/"):
                self._do_kv(self.path[len("/kv/"):])
                return
            try:
                query, group = self._body(), self._group()
                dl = self._deadline_ms()
                if self._shed_expired(dl):
                    return
                fut = rdb.propose(query, group, token=self._retry_token(),
                                  **({} if dl is None
                                     else {"deadline_ms": dl}))
                try:
                    err = fut.wait(timeout_s if dl is None
                                   else min(timeout_s, dl / 1000.0))
                except TimeoutError:
                    # Deregister the ack so it cannot leak (the statement
                    # may still commit later; only this client gave up).
                    rdb.abandon(query, group, fut)
                    if dl is not None:
                        ov = getattr(rdb.pipe.node, "overload", None)
                        if ov is not None:
                            ov.note_shed("commit_wait")
                    raise
            except Overloaded as e:
                self._refuse(e)
                return
            except NotLeaderError as e:
                # The --pod deployment refuses writes for groups owned
                # by another pod host up front (server/main.py
                # PodRaftDB); answer like a non-leader linearizable
                # read so the client chases X-Raft-Leader (the 1-based
                # slot in the pod hosts table).
                self._send(421, (str(e) + "\n").encode("utf-8"),
                           headers={"X-Raft-Leader": str(e.leader)}
                           if e.leader > 0 else None)
                return
            except TimeoutError as e:
                # Retryable: commit or apply did not land in budget —
                # 503 + Retry-After via the unified refusal helper.
                self._refuse(e)
                return
            except Exception as e:
                self._err(e)
                return
            if err is not None:
                if isinstance(err, Overloaded):
                    self._refuse(err)
                else:
                    self._err(err)
            else:
                # The ack implies local apply: the watermark echoed
                # here covers this very write (X-Raft-Session —
                # present it on a session read for read-your-writes).
                self._send(204, headers=_session_headers(rdb, group))

        def do_GET(self):
            if self.path.startswith("/kv/"):
                self._do_kv(self.path[len("/kv/"):])
                return
            if self.path == "/healthz":
                # Readiness: id, per-group role/leader/term/applied.
                # Answering at all proves boot + replay completed (the
                # nemesis's restart-detection probe, no write needed).
                self._body()    # drain — keep-alive
                self._send(200, rdb.render_health().encode(),
                           ctype="application/json")
                return
            if self.path.partition("?")[0] == "/metrics":
                # Content negotiation (utils/metrics.py wants_prom):
                # ?format=prom or a Prometheus/OpenMetrics Accept
                # header gets the text exposition; default stays JSON.
                from raftsql_tpu.utils.metrics import (PROM_CONTENT_TYPE,
                                                       wants_prom)
                self._body()    # drain — a leftover body corrupts keep-alive
                if wants_prom(self.path.partition("?")[2],
                              self.headers.get("Accept", "")):
                    self._send(200, rdb.render_metrics_prom().encode(),
                               ctype=PROM_CONTENT_TYPE)
                else:
                    self._send(200, rdb.render_metrics().encode(),
                               ctype="application/json")
                return
            if self.path == "/trace":
                # Chrome trace-event JSON (Perfetto-loadable): the span
                # tracer + device event ring (raftsql_tpu/obs/).  Valid
                # empty document while tracing is off (the default).
                self._body()    # drain — keep-alive
                self._send(200, rdb.render_trace().encode(),
                           ctype="application/json")
                return
            if self.path == "/events":
                self._body()    # drain — keep-alive
                self._send(200, rdb.render_events().encode(),
                           ctype="application/json")
                return
            if self.path == "/members":
                # Membership admin read (raftsql_tpu/membership/):
                # per-group active config, joint state, leader hint.
                self._body()    # drain — keep-alive
                self._send(200, rdb.render_members().encode(),
                           ctype="application/json")
                return
            try:
                # X-Consistency selects the read mode (README
                # read-modes table): local (default) / session /
                # follower / linear.  X-Raft-Session carries the
                # session watermark (the commit-watermark echo a
                # previous response returned).
                mode = (self.headers.get("X-Consistency", "")
                        .lower() or "local")
                wm = int(self.headers.get("X-Raft-Session") or 0)
                group = self._group()
                body = self._body()
                dl = self._deadline_ms()
                if self._shed_expired(dl):
                    return
                served: dict = {}
                rows = rdb.query(body, group, timeout=timeout_s,
                                 mode=mode, watermark=wm,
                                 deadline_ms=dl,
                                 brownout=self._brownout_ok(),
                                 info=served)
            except Overloaded as e:
                # Admission refusal or brownout without opt-in: 429 +
                # jittered Retry-After — never a silent downgrade.
                self._refuse(e)
                return
            except NotLeaderError as e:
                # 421 Misdirected Request + the leader hint: the client
                # retries its linearizable read against that node.
                self._send(421, (str(e) + "\n").encode("utf-8"),
                           headers={"X-Raft-Leader": str(e.leader)}
                           if e.leader > 0 else None)
                return
            except TimeoutError as e:
                # Transient server-side condition (quorum unreachable or
                # apply lagging) — retryable, NOT a client error.
                self._refuse(e)
                return
            except Exception as e:
                self._err(e)
                return
            # Commit-watermark echo: the client's next session read
            # presents this to get read-your-writes anywhere.
            hdrs = _session_headers(rdb, group) or {}
            if served.get("served"):
                # The brownout contract: the response always names the
                # mode it was actually served at.
                hdrs["X-Raft-Served-Mode"] = served["served"]
            self._send(200, rows.encode("utf-8"), headers=hdrs)

        def _method_not_allowed(self):
            self._body()    # drain — a leftover body corrupts keep-alive
            self.send_response(405)
            self.send_header("Allow", "PUT, GET")
            body = b"Method not allowed\n"
            # HEAD responses must carry no body (a written body would be
            # parsed as the next response on a keep-alive connection).
            if self.command == "HEAD":
                body = b""
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            if body:
                self.wfile.write(body)

        def do_POST(self):
            # Admin writes: POST /members
            # {"group": 0, "op": "add|add_learner|promote|remove|
            #  remove_learner", "peer": <slot>} and POST /transfer
            # {"group": 0, "target": <slot>} (graceful leadership
            # transfer, thesis §3.10).  Leader-only: elsewhere answers
            # 421 + X-Raft-Leader like linearizable reads.
            if self.path not in ("/members", "/transfer", "/reshard"):
                self._method_not_allowed()
                return
            try:
                req = json.loads(self._body() or "{}")
                if self.path == "/reshard":
                    # Elastic-keyspace verb: {"verb": "split|merge|
                    # migrate", "src": g, "dst": g|peer, "slots":
                    # [..]?}.  One verb in flight: busy answers 409;
                    # no plane compiled in answers 503.
                    if rdb.reshard is None:
                        self._send(503,
                                   b"no reshard plane (--reshard)\n")
                        return
                    from raftsql_tpu.reshard.coordinator import (
                        ReshardRefused)
                    try:
                        got = rdb.reshard.enqueue(
                            str(req.get("verb", "")),
                            int(req.get("src", -1)),
                            int(req.get("dst", -1)),
                            req.get("slots"))
                    except ReshardRefused as e:
                        self._send(409, (str(e) + "\n").encode())
                        return
                    self._send(200, (json.dumps(got, sort_keys=True)
                                     + "\n").encode(),
                               ctype="application/json")
                    return
                if self.path == "/transfer":
                    got = rdb.transfer(int(req.get("group", 0)),
                                       int(req.get("target", -1)))
                else:
                    got = rdb.member_change(int(req.get("group", 0)),
                                            str(req.get("op", "")),
                                            int(req.get("peer", -1)))
            except NotLeaderError as e:
                self._send(421, (str(e) + "\n").encode("utf-8"),
                           headers={"X-Raft-Leader": str(e.leader)}
                           if e.leader > 0 else None)
                return
            except Exception as e:
                self._err(e)
                return
            self._send(200, (json.dumps(got, sort_keys=True)
                             + "\n").encode(),
                       ctype="application/json")

        do_DELETE = _method_not_allowed
        do_PATCH = _method_not_allowed
        do_HEAD = _method_not_allowed

    return Handler


class _Server(ThreadingHTTPServer):
    # The stdlib default listen backlog of 5 resets connections when a
    # burst of concurrent keep-alive clients arrives; scoped here so no
    # other ThreadingHTTPServer in the process is affected.
    request_queue_size = 256


class SQLServer:
    """Stoppable HTTP server (the reference's stoppable listener pattern,
    listener.go:25-59, applied to the client API)."""

    def __init__(self, port: int, rdb: RaftDB, host: str = "",
                 timeout_s: float = 30.0):
        self.httpd = _Server((host, port), _make_handler(rdb, timeout_s))
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def serve_forever(self) -> None:
        self.httpd.serve_forever()

    def start(self) -> None:
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True, name="sql-http")
        self._thread.start()

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)


def serve_http_sql_api(port: int, rdb: RaftDB) -> None:
    """Blocking entry point, mirroring ServeHttpSqlAPI
    (reference httpapi.go:71-79)."""
    SQLServer(port, rdb).serve_forever()
