"""Hardened HTTP client: retries, leader caching, exactly-once PUTs.

The README's curl recipe and the process tests talked to the cluster
with ad-hoc helpers that could only retry a PUT while the connection
was REFUSED — once a server had accepted the bytes, a re-send risked a
duplicate apply (the reference's content-keyed ack model has no request
identity, db.go:112-118).  This client closes that gap and is what both
the process-plane chaos nemesis (chaos/proc.py) and operators should
use:

  * per-request timeouts — a stalled (SIGSTOPped) server costs one
    timeout, not a hung client;
  * jittered exponential backoff across retries, rotating through the
    cluster's nodes so a dead node is routed around;
  * leader caching: a 421 Misdirected Request carries X-Raft-Leader
    (linearizable reads, membership writes) — the hint is remembered
    per group and tried first next time;
  * PROACTIVE routing hints (PR 12): /healthz publishes each group's
    role/leader plus the node's remaining read-lease seconds; the
    client sweeps it (refresh_hints) so writes go leader-first and
    linear reads go lease-holder-first WITHOUT paying a 421 round
    trip at all in steady state;
  * RETRY TOKENS: every logical PUT draws one 64-bit token, sent as
    X-Raft-Retry-Token on every attempt.  The server pins the
    proposal's envelope id to it (runtime/envelope.py), so however many
    attempts reach however many leaders across crashes and failovers,
    the statement applies EXACTLY ONCE — which is what makes
    retry-after-accept safe at all.

Deterministic apply errors (HTTP 400) are never retried — the statement
itself is wrong and a re-send cannot fix it.  503 (quorum/apply
timeout), 421, connection errors, and request timeouts are retried
until the caller's deadline.
"""
from __future__ import annotations

import http.client
import random
import secrets
import socket
import time
from typing import Dict, List, Optional, Tuple


class ClientError(Exception):
    """Base class for terminal client failures."""


class SQLError(ClientError):
    """The server answered 400: the statement failed deterministically
    (bad SQL, apply error).  Retrying cannot help."""

    def __init__(self, status: int, text: str):
        super().__init__(f"HTTP {status}: {text.strip()}")
        self.status = status
        self.text = text


class Unavailable(ClientError):
    """No node produced a definitive answer before the deadline."""


_RETRYABLE_OS = (ConnectionRefusedError, ConnectionResetError,
                 BrokenPipeError, socket.timeout, TimeoutError, OSError)


class _NodePool:
    """Keep-alive connection pool for ONE node: bounded concurrency
    (the semaphore is the per-node in-flight cap — a loadgen with 500
    threads cannot open 500 sockets to one server), idle connections
    reused LIFO (warmest first).  http.client connections are not
    thread-safe; each is owned by exactly one borrower at a time."""

    def __init__(self, host: str, port: int, max_conns: int,
                 max_idle: int):
        import threading
        self.host, self.port = host, port
        self._idle: List[http.client.HTTPConnection] = []
        self._mu = threading.Lock()
        self._sem = threading.BoundedSemaphore(max_conns)
        self._max_idle = max_idle

    def acquire(self, timeout_s: float):
        """(conn, reused): a pooled keep-alive connection when one is
        idle, else a fresh one.  Blocks while the node is at its
        concurrency cap."""
        if not self._sem.acquire(timeout=timeout_s):
            raise socket.timeout(
                f"{self.host}:{self.port}: per-node concurrency cap")
        with self._mu:
            conn = self._idle.pop() if self._idle else None
        if conn is not None:
            conn.timeout = timeout_s
            if conn.sock is not None:
                conn.sock.settimeout(timeout_s)
            return conn, True
        return http.client.HTTPConnection(
            self.host, self.port, timeout=timeout_s), False

    def release(self, conn, keep: bool) -> None:
        if keep:
            with self._mu:
                if len(self._idle) < self._max_idle:
                    self._idle.append(conn)
                    conn = None
        if conn is not None:
            try:
                conn.close()
            except OSError:          # pragma: no cover - teardown race
                pass
        self._sem.release()

    def close(self) -> None:
        with self._mu:
            idle, self._idle = self._idle, []
        for c in idle:
            try:
                c.close()
            except OSError:          # pragma: no cover - teardown race
                pass


class RaftSQLClient:
    """Client for one cluster: `nodes` is a list of "host:port" (or
    bare port numbers, meaning localhost) client-API endpoints, indexed
    the way the caller thinks of node ids (0-based).

    Connection handling is a load-balancing POOL (PR 7): keep-alive
    connections per node reused across requests (the old one-connection
    -per-request shape spent most of a small PUT's budget on TCP
    setup/teardown), per-node in-flight caps, and a leader cache + RR
    cursor shared THREAD-SAFELY across every thread using this client
    — a bench loadgen drives one client object from hundreds of
    workers.  A request that fails on a REUSED connection (the server
    closed the idle socket) transparently retries once on a fresh
    connection before surfacing the error; fresh-connection failures
    surface unchanged, so the callers' retry policies see exactly the
    old contract."""

    def __init__(self, nodes: List, timeout_s: float = 10.0,
                 backoff_s: float = 0.05, backoff_cap_s: float = 1.0,
                 rng: Optional[random.Random] = None,
                 max_conns_per_node: int = 64,
                 max_idle_per_node: int = 32,
                 hint_refresh_s: float = 2.0):
        import threading
        self.nodes: List[Tuple[str, int]] = []
        for n in nodes:
            if isinstance(n, int):
                self.nodes.append(("127.0.0.1", n))
            else:
                host, _, port = str(n).rpartition(":")
                self.nodes.append((host or "127.0.0.1", int(port)))
        self.timeout_s = timeout_s
        self.backoff_s = backoff_s
        self.backoff_cap_s = backoff_cap_s
        self.hint_refresh_s = hint_refresh_s
        self._rng = rng or random.Random()
        self._mu = threading.Lock()            # leader cache + rr cursor
        self._leader: Dict[int, int] = {}      # group -> node index
        # Overload plane (raftsql_tpu/overload/): node index -> the
        # monotonic time a 429/503 Retry-After holds that node out of
        # the rotation until.  Per-node, so one saturated engine is
        # avoided while its peers keep serving — never a retry storm.
        self._holdoff: Dict[int, float] = {}
        self._lease: Dict[int, Tuple[int, float]] = {}
        #   group -> (node index, monotonic lease-hint expiry)
        # Witness replicas (config.py quorum geometry): they accept
        # forwarded writes like any follower but refuse every read
        # (400), so the read rotation must never land on one.
        self._witness: set = set()
        self._hints_at = 0.0                   # last /healthz sweep
        self._keymap: Optional[dict] = None    # elastic-keyspace doc
        self._rr = 0                           # round-robin cursor
        self._max_conns = max_conns_per_node   # pod-host adoption
        self._max_idle = max_idle_per_node
        self._pools = [_NodePool(h, p, max_conns_per_node,
                                 max_idle_per_node)
                       for (h, p) in self.nodes]
        # Read-replica tier (raftsql_tpu/replica/): endpoints adopted
        # from the engines' /healthz `replica.endpoints`, routed
        # nearest-first by a measured per-endpoint RTT EWMA (CD-Raft's
        # placement story: reads go to the closest live replica, and
        # ANY refusal — the replicas' fail-closed 421 ladder — falls
        # back to the write tier).  All lists are index-aligned and
        # append-only under _mu, like the pod-host adoption above.
        self._replicas: List[Tuple[str, int]] = []
        self._rpools: List[_NodePool] = []
        self._rtt: List[Optional[float]] = []  # EWMA ms, None unmeasured
        self._ralive: List[bool] = []
        # endpoint -> [hits, refusals]: the georeads bench's evidence
        # of which replica served what.
        self.replica_stats: Dict[str, List[int]] = {}

    def close(self) -> None:
        for p in self._pools + self._rpools:
            p.close()

    # -- low-level -----------------------------------------------------

    def raw(self, node: int, method: str, path: str = "/",
            body: str = "", headers: Optional[dict] = None,
            timeout_s: Optional[float] = None):
        """One request to one node, no cluster-level retries:
        (status, headers, text).  Raises the underlying OSError on
        connection trouble — the retry policy lives in the callers.
        (A stale KEEP-ALIVE socket is retried once on a fresh
        connection internally; that is connection reuse mechanics, not
        policy.)"""
        t = timeout_s or self.timeout_s
        return self._pooled(self._pools[node], method, path,
                            body, headers, t)

    def _pooled(self, pool: _NodePool, method: str, path: str,
                body: str, headers: Optional[dict], t: float):
        for attempt in (0, 1):
            conn, reused = pool.acquire(t)
            keep = False
            try:
                conn.request(method, path, body=body.encode("utf-8"),
                             headers=headers or {})
                r = conn.getresponse()
                text = r.read().decode("utf-8", "replace")
                keep = not r.will_close
                return r.status, dict(r.getheaders()), text
            except _RETRYABLE_OS:
                if reused and attempt == 0:
                    continue           # stale keep-alive: one fresh try
                raise
            except http.client.HTTPException as e:
                # A half-closed keep-alive socket surfaces as
                # BadStatusLine/RemoteDisconnected, not OSError.
                if reused and attempt == 0:
                    continue
                raise ConnectionResetError(str(e)) from e
            finally:
                pool.release(conn, keep)
        raise AssertionError("unreachable")    # pragma: no cover

    def _order(self, group: int, node: Optional[int],
               prefer: Optional[int] = None,
               for_read: bool = False) -> List[int]:
        """Attempt order: pinned node only, else `prefer` (a live lease
        hint) first, then cached leader, then round-robin over the
        rest.  `for_read` drops known witness replicas from the
        rotation (they refuse every read with 400 — a terminal answer,
        not a retry); a pinned node is the caller's explicit choice
        and is honored either way."""
        if node is not None:
            return [node]
        n = len(self.nodes)
        now = time.monotonic()
        with self._mu:
            start = self._rr % n
            self._rr += 1
            lead = self._leader.get(group)
            skip = set(self._witness) if for_read else set()
            # Retry-After holdoff: a node that refused with 429/503
            # stays out of the rotation until its estimate passes —
            # unless that would empty it (then desperation wins).
            skip |= {i for i, t in self._holdoff.items() if t > now}
        order = [(start + i) % n for i in range(n)
                 if (start + i) % n not in skip] \
            or [(start + i) % n for i in range(n)]
        for front in (lead, prefer):
            if front is not None and front in order:
                order.remove(front)
                order.insert(0, front)
        return order

    # -- routing hints (PR 12 front router) ----------------------------

    def _adopt_pod_hosts(self, hosts) -> int:
        """A pod deployment (raftsql_tpu/pod/) publishes the full host
        table in /healthz ("pod" section, --pod-id order); adopt every
        not-yet-known host so a client pointed at ONE pod host learns
        to sweep — and route to — them all.  Returns adopted count.
        Appending under _mu is safe against concurrent raw() readers
        (existing node indexes never move)."""
        added = 0
        for n in hosts:
            host, _, port = str(n).rpartition(":")
            try:
                entry = (host or "127.0.0.1", int(port))
            except ValueError:
                continue
            with self._mu:
                if entry in self.nodes:
                    continue
                self.nodes.append(entry)
                self._pools.append(_NodePool(entry[0], entry[1],
                                             self._max_conns,
                                             self._max_idle))
                added += 1
        return added

    # -- read-replica tier (raftsql_tpu/replica/) ----------------------

    def _adopt_replicas(self, endpoints) -> int:
        """Adopt replica HTTP endpoints published in an engine's
        /healthz `replica.endpoints` (each replica advertises its own
        via SUBSCRIBE).  Append-only under _mu: indexes never move, so
        concurrent raw_replica callers stay valid."""
        added = 0
        for n in endpoints or ():
            host, _, port = str(n).rpartition(":")
            try:
                entry = (host or "127.0.0.1", int(port))
            except ValueError:
                continue
            with self._mu:
                if entry in self._replicas:
                    continue
                self._replicas.append(entry)
                self._rpools.append(_NodePool(entry[0], entry[1],
                                              self._max_conns,
                                              self._max_idle))
                self._rtt.append(None)
                self._ralive.append(True)
                added += 1
        return added

    def raw_replica(self, ridx: int, method: str, path: str = "/",
                    body: str = "", headers: Optional[dict] = None,
                    timeout_s: Optional[float] = None):
        """raw(), but against replica `ridx` — and every answered
        request feeds the endpoint's RTT EWMA (the nearest-replica
        routing signal)."""
        t = timeout_s or self.timeout_s
        with self._mu:
            pool = self._rpools[ridx]
        t0 = time.monotonic()
        got = self._pooled(pool, method, path, body, headers, t)
        self._note_rtt(ridx, (time.monotonic() - t0) * 1e3)
        return got

    def _note_rtt(self, ridx: int, ms: float) -> None:
        """EWMA (alpha 0.3) of measured request wall time per replica
        endpoint; an answer also marks the endpoint live again."""
        with self._mu:
            if ridx < len(self._rtt):
                prev = self._rtt[ridx]
                self._rtt[ridx] = ms if prev is None \
                    else 0.7 * prev + 0.3 * ms
                self._ralive[ridx] = True

    def _replica_order(self) -> List[int]:
        """Live replica indexes, nearest (lowest RTT EWMA) first;
        unmeasured endpoints go last until their first probe."""
        with self._mu:
            pairs = sorted(
                (self._rtt[i] if self._rtt[i] is not None
                 else float("inf"), i)
                for i in range(len(self._replicas)) if self._ralive[i])
        return [i for _rtt, i in pairs]

    def replica_endpoints(self) -> List[str]:
        with self._mu:
            return [f"{h}:{p}" for h, p in self._replicas]

    def replica_rtt_ms(self) -> Dict[str, Optional[float]]:
        with self._mu:
            return {f"{h}:{p}": (round(self._rtt[i], 3)
                                 if self._rtt[i] is not None else None)
                    for i, (h, p) in enumerate(self._replicas)}

    def _try_replicas(self, sql: str, group: int, headers: dict):
        """One pass over the replica tier, nearest first: (rows,
        watermark) on a 200, None to fall back to the write tier.  The
        headers dict is the caller's — so session watermarks and the
        consistency mode propagate to replicas verbatim.  A 421 is the
        replica's fail-closed ladder refusing (stale epoch, uncovered
        watermark, lapsed lease, stale heartbeat): record the leader
        hint it carries and move on — the write tier is authoritative.
        Connection errors mark the endpoint dead until the next
        answered probe."""
        for ridx in self._replica_order():
            with self._mu:
                if ridx >= len(self._replicas):
                    continue
                ep = "%s:%d" % self._replicas[ridx]
            try:
                status, hdrs, text = self.raw_replica(
                    ridx, "GET", "/", sql, headers)
            except _RETRYABLE_OS:
                with self._mu:
                    if ridx < len(self._ralive):
                        self._ralive[ridx] = False
                continue
            with self._mu:
                stats = self.replica_stats.setdefault(ep, [0, 0])
                stats[0 if status == 200 else 1] += 1
            if status == 200:
                return text, self._session_of(hdrs)
            if status == 421:
                self._note_leader(group, hdrs)
        return None

    def refresh_hints(self, timeout_s: float = 1.0) -> int:
        """Sweep GET /healthz and prime the routing tables from the
        per-group rows (runtime/db.py health_doc): a node whose row
        says `role == "leader"` is the group's write target, and a node
        reporting `lease_s > 0` holds the group's read lease RIGHT NOW
        — a linear read routed there is served from the local lease
        fast path instead of paying a quorum round.  Steady state then
        has no 421 redirects at all: the first request of a fresh
        client already goes to the right node.  Returns the number of
        groups with a usable leader hint.

        Pod deployments (raftsql_tpu/pod/): a host whose /healthz
        carries a "pod" section publishes the full pod hosts table —
        the sweep ADOPTS any host it did not know (and walks it in
        this same pass), and per-group routing merges by OWNERSHIP
        instead of engine role: every pod host truthfully reports
        every group (replicated compute), but only the owner host
        serves a group (server/main.py PodRaftDB), so its `pod_owned`
        rows become the group's write/lease targets."""
        leaders: Dict[int, int] = {}
        leases: Dict[int, Tuple[int, float]] = {}
        witnesses: set = set()
        answered: set = set()
        now = time.monotonic()
        idx = 0
        while idx < len(self.nodes):   # adoption may grow the sweep
            doc = self.health(idx, timeout_s=timeout_s)
            if not doc:
                idx += 1
                continue
            answered.add(idx)
            if doc.get("witness"):
                witnesses.add(idx)
            pod = doc.get("pod")
            if pod:
                self._adopt_pod_hosts(pod.get("hosts") or ())
            # Read-replica tier: an engine with --replica-listen lists
            # the HTTP endpoints its subscribers advertised.
            rep = doc.get("replica")
            if isinstance(rep, dict):
                self._adopt_replicas(rep.get("endpoints") or ())
            for key, row in (doc.get("groups") or {}).items():
                try:
                    g = int(key)
                except (TypeError, ValueError):
                    continue
                if pod is not None:
                    if row.get("pod_owned"):
                        leaders[g] = idx     # owner host serves g
                        lease = row.get("lease_s")
                        if isinstance(lease, (int, float)) and lease > 0:
                            leases[g] = (idx, now + float(lease))
                    continue
                if row.get("role") == "leader":
                    leaders[g] = idx           # self-report wins
                else:
                    hint = row.get("leader")
                    if isinstance(hint, int) and hint > 0:
                        leaders.setdefault(g,
                                           (hint - 1) % len(self.nodes))
                lease = row.get("lease_s")
                if isinstance(lease, (int, float)) and lease > 0:
                    leases[g] = (idx, now + float(lease))
            # Elastic keyspace (raftsql_tpu/reshard/): adopt the
            # newest published key->group mapping seen on the sweep.
            self._note_keymap(doc.get("keymap"))
            idx += 1
        with self._mu:
            self._leader.update(leaders)
            self._lease.update(leases)
            # Witness identity is static per process: only nodes that
            # ANSWERED update their entry (an unreachable node keeps
            # whatever the last sweep learned).
            self._witness -= answered
            self._witness |= witnesses
            self._hints_at = time.monotonic()
        # Probe adopted replicas once per sweep: seeds the RTT EWMA
        # (nearest-first routing needs a measurement) and revives
        # endpoints marked dead by a connection error.
        with self._mu:
            n_rep = len(self._replicas)
        for ridx in range(n_rep):
            try:
                self.raw_replica(ridx, "GET", "/healthz",
                                 timeout_s=timeout_s)
            except _RETRYABLE_OS:
                with self._mu:
                    if ridx < len(self._ralive):
                        self._ralive[ridx] = False
        return len(leaders)

    def _maybe_refresh_hints(self, group: int) -> None:
        """Opportunistic hint sweep: only when the group has no cached
        leader AND the last sweep is stale — a warm cache costs
        nothing, and 421 hints keep it warm between sweeps."""
        with self._mu:
            if group in self._leader or (
                    time.monotonic() - self._hints_at
                    < self.hint_refresh_s):
                return
        self.refresh_hints(timeout_s=0.5)

    def _lease_target(self, group: int) -> Optional[int]:
        """Node index holding a still-live lease hint for `group`, or
        None.  Hints are short (the engine caps published deadlines at
        its lease horizon) — an expired hint is simply ignored."""
        with self._mu:
            hint = self._lease.get(group)
        if hint is not None and time.monotonic() < hint[1]:
            return hint[0]
        return None

    def _note_leader(self, group: int, headers: dict) -> bool:
        """Record a 421's X-Raft-Leader hint.  Returns True when the
        hint names a DIFFERENT node than the cache — the caller should
        abandon the current rotation and retry at the new leader
        immediately (a graceful transfer moved leadership mid-request,
        PR 11).  A 421 WITHOUT a usable hint invalidates the cache
        instead: the node we believed led the group demonstrably does
        not, and pinning it first would only repeat the miss."""
        hint = headers.get("X-Raft-Leader")
        if hint and hint.isdigit() and int(hint) > 0:
            idx = (int(hint) - 1) % len(self.nodes)
            with self._mu:
                changed = self._leader.get(group) != idx
                self._leader[group] = idx
            return changed
        with self._mu:
            self._leader.pop(group, None)
        return False

    def _note_retry_after(self, idx: int, headers: dict) -> None:
        """Honor a 429/503 Retry-After (decimal seconds): hold THIS
        node out of the rotation until the server's estimate passes.
        Other nodes are still tried immediately — per-node backoff,
        not a global stall."""
        ra = headers.get("Retry-After")
        if not ra:
            return
        try:
            delay = min(float(ra), 30.0)
        except ValueError:
            return
        if delay <= 0:
            return
        until = time.monotonic() + delay
        with self._mu:
            if until > self._holdoff.get(idx, 0.0):
                self._holdoff[idx] = until

    def _sleep_backoff(self, attempt: int, deadline: float) -> bool:
        """Jittered exponential backoff; False when the deadline would
        pass before the sleep ends."""
        delay = min(self.backoff_cap_s,
                    self.backoff_s * (2 ** min(attempt, 8)))
        delay *= 0.5 + self._rng.random()      # 0.5x .. 1.5x jitter
        if time.monotonic() + delay >= deadline:
            return False
        time.sleep(delay)
        return True

    # -- public API ----------------------------------------------------

    @staticmethod
    def _session_of(hdrs: dict) -> Optional[int]:
        wm = hdrs.get("X-Raft-Session")
        if wm is not None and wm.isdigit():
            return int(wm)
        return None

    def put(self, sql: str, group: int = 0, node: Optional[int] = None,
            deadline_s: float = 60.0,
            token: Optional[int] = None) -> Optional[int]:
        """Write SQL through consensus; returns once SOME attempt was
        acked (204).  Safe to retry past acceptance: every attempt
        carries the same retry token, so duplicates collapse server-side
        to one apply.  400 raises SQLError immediately (deterministic);
        everything else retries until the deadline.

        Returns the acking node's X-Raft-Session commit watermark
        (None on older servers): present it on a `consistency="session"`
        get() to read-your-write from ANY replica."""
        token = secrets.randbits(64) if token is None else token
        headers = {"X-Raft-Retry-Token": f"{token:016x}"}
        if group:
            headers["X-Raft-Group"] = str(group)
        deadline = time.monotonic() + deadline_s
        attempt = 0
        last: object = None
        if node is None:
            self._maybe_refresh_hints(group)
        while True:
            for idx in self._order(group, node):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break     # fail fast below — no network round trip
                # End-to-end deadline propagation: the server sheds
                # this attempt once the budget is spent (edge / ring /
                # stage) instead of burning WAL cost on a dead request.
                headers["X-Raft-Deadline-Ms"] = str(
                    max(int(remaining * 1000), 1))
                try:
                    status, hdrs, text = self.raw(
                        idx, "PUT", "/", sql, headers)
                except _RETRYABLE_OS as e:
                    last = e
                    continue
                if status == 204:
                    return self._session_of(hdrs)
                if status == 400:
                    raise SQLError(status, text)
                if status == 421:
                    # A hint naming a node OTHER than the cached leader
                    # means leadership moved (graceful transfer): chase
                    # it immediately instead of finishing the rotation.
                    if self._note_leader(group, hdrs) and node is None:
                        last = (status, text.strip())
                        break
                if status in (429, 503):
                    self._note_retry_after(idx, hdrs)
                last = (status, text.strip())
            attempt += 1
            if time.monotonic() >= deadline \
                    or not self._sleep_backoff(attempt, deadline):
                raise Unavailable(
                    f"PUT {sql!r} (group {group}): no ack before "
                    f"deadline; last={last!r}")

    def get(self, sql: str, group: int = 0, node: Optional[int] = None,
            linear: bool = False, deadline_s: float = 60.0,
            consistency: Optional[str] = None,
            session: int = 0) -> str:
        """Read SQL (idempotent — free to retry).  `consistency` picks
        the read mode (local/session/follower/linear; linear=True is
        shorthand for "linear"); `session` carries the X-Raft-Session
        watermark a previous response returned.  421 redirects chase
        X-Raft-Leader."""
        return self.get_session(sql, group=group, node=node,
                                linear=linear, deadline_s=deadline_s,
                                consistency=consistency,
                                session=session)[0]

    def get_session(self, sql: str, group: int = 0,
                    node: Optional[int] = None, linear: bool = False,
                    deadline_s: float = 60.0,
                    consistency: Optional[str] = None,
                    session: int = 0) -> Tuple[str, Optional[int]]:
        """get(), returning (rows, response watermark): the watermark
        is the serving replica's X-Raft-Session echo — carry the max
        of these into later session reads for monotonic reads."""
        headers = {}
        if group:
            headers["X-Raft-Group"] = str(group)
        if consistency is None and linear:
            consistency = "linear"
        if consistency and consistency != "local":
            headers["X-Consistency"] = consistency
        if session > 0:
            headers["X-Raft-Session"] = str(session)
        deadline = time.monotonic() + deadline_s
        attempt = 0
        last: object = None
        if node is None:
            self._maybe_refresh_hints(group)
            # Read-replica tier: route to the nearest live replica
            # first (RTT EWMA, CD-Raft style).  The shared headers
            # carry the session watermark and consistency mode
            # verbatim; ANY refusal (the replica's fail-closed ladder
            # answers 421, never a stale row) falls through to the
            # write tier below.
            if self._replicas:
                got = self._try_replicas(sql, group, headers)
                if got is not None:
                    return got
        while True:
            # Linear reads chase the lease holder first: served there,
            # the read needs no quorum round at all (lease fast path).
            prefer = (self._lease_target(group)
                      if consistency == "linear" else None)
            for idx in self._order(group, node, prefer=prefer,
                                   for_read=True):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break     # fail fast below — no network round trip
                headers["X-Raft-Deadline-Ms"] = str(
                    max(int(remaining * 1000), 1))
                try:
                    status, hdrs, text = self.raw(
                        idx, "GET", "/", sql, headers)
                except _RETRYABLE_OS as e:
                    last = e
                    continue
                if status == 200:
                    return text, self._session_of(hdrs)
                if status == 400:
                    raise SQLError(status, text)
                if status == 421:
                    # Non-leader for a linear read: chase the hint
                    # immediately (no backoff — the leader is up).
                    if self._note_leader(group, hdrs) and node is None:
                        break
                if status in (429, 503):
                    self._note_retry_after(idx, hdrs)
                last = (status, text.strip())
            attempt += 1
            if time.monotonic() >= deadline \
                    or not self._sleep_backoff(attempt, deadline):
                raise Unavailable(
                    f"GET {sql!r} (group {group}): no answer before "
                    f"deadline; last={last!r}")

    # -- elastic keyspace (/kv surface, raftsql_tpu/reshard/) ----------

    def _note_keymap(self, doc) -> bool:
        """Adopt a key->group mapping document if it is NEWER than the
        cached one (mapping epochs only move forward — a stale sweep
        must not roll the router back).  Returns True on adoption."""
        if not isinstance(doc, dict) or "epoch" not in doc:
            return False
        with self._mu:
            have = self._keymap
            if have is not None \
                    and int(have.get("epoch", -1)) >= int(doc["epoch"]):
                return False
            self._keymap = doc
            return True

    def keymap_epoch(self) -> Optional[int]:
        """The cached mapping version, or None before any /kv traffic
        or /healthz sweep saw a reshard-enabled node."""
        with self._mu:
            return (int(self._keymap["epoch"])
                    if self._keymap is not None else None)

    def refresh_keymap(self, timeout_s: float = 1.0) -> Optional[int]:
        """Sweep /healthz for the current key->group mapping (the
        unknown-group recovery path after a split/merge moved the
        keyspace under this client).  Returns the adopted epoch."""
        for idx in range(len(self.nodes)):
            doc = self.health(idx, timeout_s=timeout_s)
            if doc:
                self._note_keymap(doc.get("keymap"))
        return self.keymap_epoch()

    def _kv_headers(self) -> dict:
        headers = {}
        epoch = self.keymap_epoch()
        if epoch is not None:
            headers["X-Raft-Keymap-Epoch"] = str(epoch)
        return headers

    def _note_kv_epoch(self, hdrs: dict) -> None:
        """Every /kv response echoes the epoch it served under; a
        NEWER one than our cache means the keyspace moved (split/merge
        behind our back) — sweep /healthz for the full mapping so
        subsequent requests pin the current epoch."""
        e = hdrs.get("X-Raft-Keymap-Epoch")
        if e is None or not e.isdigit():
            return
        have = self.keymap_epoch()
        if have is None or int(e) > have:
            self.refresh_keymap()

    def _kv_refused(self, status: int, text: str) -> bool:
        """Handle a 409 mapping-epoch refusal: adopt the server's
        CURRENT keymap from the response body (fallback: a /healthz
        sweep) and tell the caller to retry immediately."""
        import json
        if status != 409:
            return False
        try:
            self._note_keymap(json.loads(text).get("keymap"))
        except ValueError:
            self.refresh_keymap()
        return True

    def put_kv(self, key: str, value: str,
               deadline_s: float = 60.0,
               token: Optional[int] = None) -> Optional[int]:
        """Keyed write over the elastic keyspace (PUT /kv/<key>): the
        server routes by hash slot under its CURRENT mapping; this
        client pins the epoch it believes in and fails closed — a 409
        (the mapping moved: split/merge/migrate) refreshes the cache
        and retries, a frozen-slot 503 backs off until the verb
        resolves.  Exactly-once via the same retry-token contract as
        put()."""
        from urllib.parse import quote
        token = secrets.randbits(64) if token is None else token
        deadline = time.monotonic() + deadline_s
        attempt = 0
        last: object = None
        path = "/kv/" + quote(key, safe="")
        while True:
            headers = self._kv_headers()
            headers["X-Raft-Retry-Token"] = f"{token:016x}"
            for idx in self._order(0, None):
                try:
                    status, hdrs, text = self.raw(
                        idx, "PUT", path, value, headers)
                except _RETRYABLE_OS as e:
                    last = e
                    continue
                if status == 204:
                    self._note_kv_epoch(hdrs)
                    return self._session_of(hdrs)
                if self._kv_refused(status, text):
                    last = (status, "keymap moved")
                    break              # re-route under the new mapping
                if status == 400:
                    raise SQLError(status, text)
                if status in (429, 503):
                    self._note_retry_after(idx, hdrs)
                last = (status, text.strip())
            attempt += 1
            if time.monotonic() >= deadline \
                    or not self._sleep_backoff(attempt, deadline):
                raise Unavailable(
                    f"PUT /kv/{key}: no ack before deadline; "
                    f"last={last!r}")

    def get_kv(self, key: str, deadline_s: float = 60.0,
               consistency: Optional[str] = None,
               session: int = 0) -> Optional[str]:
        """Keyed read (GET /kv/<key>): the value, or None when the key
        does not exist.  Same mapping-epoch fail-closed handling as
        put_kv."""
        from urllib.parse import quote
        deadline = time.monotonic() + deadline_s
        attempt = 0
        last: object = None
        path = "/kv/" + quote(key, safe="")
        while True:
            headers = self._kv_headers()
            if consistency and consistency != "local":
                headers["X-Consistency"] = consistency
            if session > 0:
                headers["X-Raft-Session"] = str(session)
            for idx in self._order(0, None, for_read=True):
                try:
                    status, hdrs, text = self.raw(
                        idx, "GET", path, "", headers)
                except _RETRYABLE_OS as e:
                    last = e
                    continue
                if status == 200:
                    self._note_kv_epoch(hdrs)
                    return text
                if status == 404:
                    self._note_kv_epoch(hdrs)
                    return None
                if self._kv_refused(status, text):
                    last = (status, "keymap moved")
                    break
                if status == 400:
                    raise SQLError(status, text)
                if status in (429, 503):
                    self._note_retry_after(idx, hdrs)
                last = (status, text.strip())
            attempt += 1
            if time.monotonic() >= deadline \
                    or not self._sleep_backoff(attempt, deadline):
                raise Unavailable(
                    f"GET /kv/{key}: no answer before deadline; "
                    f"last={last!r}")

    def reshard(self, verb: str, src: int, dst: int, slots=None,
                node: Optional[int] = None,
                deadline_s: float = 10.0) -> dict:
        """POST /reshard: enqueue an elastic-keyspace verb.  409 (a
        verb already in flight) surfaces as SQLError — the caller
        decides whether to wait."""
        import json
        body = json.dumps({"verb": verb, "src": src, "dst": dst,
                           "slots": slots})
        deadline = time.monotonic() + deadline_s
        attempt = 0
        last: object = None
        while True:
            for idx in self._order(0, node):
                try:
                    status, _hdrs, text = self.raw(
                        idx, "POST", "/reshard", body)
                except _RETRYABLE_OS as e:
                    last = e
                    continue
                if status == 200:
                    return json.loads(text)
                if status in (400, 409):
                    raise SQLError(status, text)
                last = (status, text.strip())
            attempt += 1
            if time.monotonic() >= deadline \
                    or not self._sleep_backoff(attempt, deadline):
                raise Unavailable(
                    f"POST /reshard {verb}: no answer; last={last!r}")

    def get_until(self, sql: str, want: str, group: int = 0,
                  node: Optional[int] = None,
                  deadline_s: float = 60.0,
                  poll_s: float = 0.25) -> str:
        """Poll an idempotent read until the answer matches `want`
        (replication is async — the reference's own tests poll the same
        way, raftsql_test.go:159-170)."""
        deadline = time.monotonic() + deadline_s
        last: object = None
        while time.monotonic() < deadline:
            try:
                got = self.get(sql, group=group, node=node,
                               deadline_s=min(
                                   5.0, max(0.1,
                                            deadline - time.monotonic())))
                if got == want:
                    return got
                last = got
            except (Unavailable, SQLError) as e:
                last = e
            time.sleep(poll_s)
        raise Unavailable(f"GET {sql!r}: wanted {want!r}, last={last!r}")

    def health(self, node: int,
               timeout_s: float = 2.0) -> Optional[dict]:
        """GET /healthz of one node; None when unreachable/stalled (a
        SIGSTOPped process simply eats the timeout)."""
        import json
        try:
            status, _, text = self.raw(node, "GET", "/healthz",
                                       timeout_s=timeout_s)
        except _RETRYABLE_OS:
            return None
        if status != 200:
            return None
        try:
            return json.loads(text)
        except ValueError:
            return None

    def wait_healthy(self, node: int, deadline_s: float = 30.0,
                     poll_s: float = 0.2) -> dict:
        """Block until the node answers /healthz (restart detection —
        the probe the nemesis uses instead of a write)."""
        deadline = time.monotonic() + deadline_s
        while True:
            doc = self.health(node)
            if doc is not None:
                return doc
            if time.monotonic() >= deadline:
                raise Unavailable(f"node {node}: /healthz never came up")
            time.sleep(poll_s)
