"""Single-threaded asyncio HTTP plane — same client API as api/http.py.

The reference serves its HTTP API with Go's net/http (one goroutine per
connection, reference httpapi.go:26-79).  The stdlib-threaded port of
that shape (api/http.py) spends most of its request budget on thread
machinery once clients are concurrent: one OS thread per connection
contending for the GIL with the consensus tick thread, plus one
Event.wait/set round trip per acknowledged proposal.

This plane is the event-loop redesign: ONE thread runs a minimal
HTTP/1.1 state machine for every connection, proposals go straight to
`RaftDB.propose`, and commit acknowledgements ride a BATCHED bridge —
the consensus consumer resolves AckFutures from its own thread, the
bridge coalesces every ack that lands between two loop iterations into
a single `call_soon_threadsafe` wakeup (one loop wakeup per commit
batch, not per request).  Reads (which may block on SQLite or a
ReadIndex round) run in a small executor so the loop never stalls.

Semantics parity with api/http.py, pinned by the parametrized fixture
in tests/test_api_http.py (every test runs against both planes):
PUT 204/400 + blocking-until-applied contract (reference
httpapi.go:38-49), GET local reads + X-Consistency: linear (421 +
X-Raft-Leader elsewhere, 503 on timeout), GET /metrics, 405 with Allow
on anything else (connection stays usable), X-Raft-Group routing.
"""
from __future__ import annotations

import asyncio
import logging
import threading
from typing import Optional

from raftsql_tpu.overload import (Overloaded, retry_after_header,
                                  retryable_refusal)
from raftsql_tpu.runtime.db import NotLeaderError, RaftDB

log = logging.getLogger("raftsql.api.aio")

_MAX_HEAD = 64 * 1024          # header block cap before we drop the conn
_MAX_BODY = 4 * 1024 * 1024    # SQL statement cap (parity: unbounded-ish)
_ALLOW = (b"HTTP/1.1 405 Method Not Allowed\r\nAllow: PUT, GET\r\n"
          b"Content-Length: 19\r\n\r\nMethod not allowed\n")
_ALLOW_NOBODY = (b"HTTP/1.1 405 Method Not Allowed\r\nAllow: PUT, GET\r\n"
                 b"Content-Length: 0\r\n\r\n")
_204 = b"HTTP/1.1 204 No Content\r\n\r\n"


def _resp(code: int, reason: bytes, body: bytes = b"",
          ctype: bytes = b"text/plain; charset=utf-8",
          extra: tuple = ()) -> bytes:
    head = [b"HTTP/1.1 " + str(code).encode() + b" " + reason]
    for k, v in extra:
        head.append(k + b": " + v)
    head.append(b"Content-Type: " + ctype)
    head.append(b"Content-Length: " + str(len(body)).encode())
    head.append(b"")
    return b"\r\n".join(head) + b"\r\n" + body


def _refusal_resp(e: Exception) -> bytes:
    """THE retryable-refusal response for this plane — the same
    contract api/http.py emits via its `_refuse` helper: `Overloaded`
    is 429 with the controller's jittered drain-rate Retry-After,
    every other transient condition is 503 with its default; both
    ALWAYS carry Retry-After so api/client.py holds off per-node."""
    code, retry_s = retryable_refusal(e)
    reason = (b"Too Many Requests" if code == 429
              else b"Service Unavailable")
    return _resp(code, reason, (str(e) + "\n").encode(),
                 extra=((b"Retry-After",
                         retry_after_header(retry_s).encode()),))


def _session_extra(rdb, group: int) -> tuple:
    """X-Raft-Session commit-watermark echo as a `_resp` extra-header
    tuple (advisory — a failed gauge read never fails the request)."""
    try:
        return ((b"X-Raft-Session", str(rdb.watermark(group)).encode()),)
    except Exception:                                   # noqa: BLE001
        return ()


class _AckBridge:
    """Batch cross-thread ack delivery into the event loop.

    AckFuture callbacks fire on the commit-consumer thread, one per
    request; waking the loop per request would re-create the per-ack
    syscall the redesign removes.  Every ack landing while a flush is
    pending is appended under the lock and delivered by the SAME
    scheduled flush — one loop wakeup per commit batch under load."""

    def __init__(self, loop: asyncio.AbstractEventLoop):
        self.loop = loop
        self._mu = threading.Lock()
        self._pending: list = []
        self._scheduled = False

    def deliver(self, afut: asyncio.Future, err) -> None:
        with self._mu:
            self._pending.append((afut, err))
            if self._scheduled:
                return
            self._scheduled = True
        try:
            self.loop.call_soon_threadsafe(self._flush)
        except RuntimeError:     # loop closed during shutdown
            with self._mu:       # un-mute: a live loop must reschedule
                self._scheduled = False

    def _flush(self) -> None:
        with self._mu:
            items, self._pending = self._pending, []
            self._scheduled = False
        for afut, err in items:
            if not afut.done():
                afut.set_result(err)


class _Conn(asyncio.Protocol):
    """One HTTP/1.1 keep-alive connection: sequential request/response
    (pipelined bytes buffer and are parsed as soon as the in-flight
    response is written)."""

    def __init__(self, srv: "AioSQLServer"):
        self.srv = srv
        self.buf = bytearray()
        self.busy = False      # a request handler owns the connection
        self.closed = False

    # -- transport events ----------------------------------------------

    def connection_made(self, transport) -> None:
        self.tr = transport
        try:
            import socket
            sock = transport.get_extra_info("socket")
            if sock is not None:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:          # pragma: no cover - platform quirk
            pass

    def connection_lost(self, exc) -> None:
        self.closed = True

    def data_received(self, data: bytes) -> None:
        self.buf += data
        if not self.busy:
            self._pump()

    # -- request framing -----------------------------------------------

    def _pump(self) -> None:
        while not self.busy and not self.closed:
            req = self._parse_one()
            if req is None:
                return
            method, path, headers, body = req
            if path.startswith(b"/kv/") and method in (b"PUT", b"GET"):
                # Keyed surface over the elastic keyspace
                # (raftsql_tpu/reshard/): routed by hash slot through
                # the reshard plane's keymap, epoch fail-closed.
                self.busy = True
                self.srv.loop.create_task(
                    self._do_kv(method, path, headers, body))
            elif method == b"PUT":
                self.busy = True
                self.srv.loop.create_task(self._do_put(headers, body))
            elif method == b"GET":
                if path == b"/healthz":
                    # Readiness probe — parity with api/http.py.
                    self.tr.write(_resp(200, b"OK",
                                        self.srv.rdb.render_health()
                                        .encode(), b"application/json"))
                    continue
                if path.partition(b"?")[0] == b"/metrics":
                    # Prometheus negotiation, parity with api/http.py:
                    # ?format=prom or an OpenMetrics Accept header.
                    from raftsql_tpu.utils.metrics import (
                        PROM_CONTENT_TYPE, wants_prom)
                    if wants_prom(
                            path.partition(b"?")[2].decode("latin-1"),
                            headers.get("accept", "")):
                        payload = self.srv.rdb.render_metrics_prom() \
                            .encode()
                        self.tr.write(_resp(
                            200, b"OK", payload,
                            PROM_CONTENT_TYPE.encode("latin-1")))
                    else:
                        payload = self.srv.rdb.render_metrics().encode()
                        self.tr.write(_resp(200, b"OK", payload,
                                            b"application/json"))
                    continue
                if path in (b"/trace", b"/events"):
                    # Observability exports (raftsql_tpu/obs/): Chrome
                    # trace JSON / raw event rows, parity with the
                    # threaded plane.
                    render = (self.srv.rdb.render_trace
                              if path == b"/trace"
                              else self.srv.rdb.render_events)
                    self.tr.write(_resp(200, b"OK", render().encode(),
                                        b"application/json"))
                    continue
                if path == b"/members":
                    # Membership admin read — parity with api/http.py.
                    self.tr.write(_resp(
                        200, b"OK", self.srv.rdb.render_members()
                        .encode(), b"application/json"))
                    continue
                self.busy = True
                self.srv.loop.create_task(self._do_get(headers, body))
            elif method == b"POST" and path == b"/members":
                self.busy = True
                self.srv.loop.create_task(self._do_members(body))
            elif method == b"POST" and path == b"/transfer":
                self.busy = True
                self.srv.loop.create_task(self._do_transfer(body))
            elif method == b"POST" and path == b"/reshard":
                self.busy = True
                self.srv.loop.create_task(self._do_reshard(body))
            elif method == b"HEAD":
                self.tr.write(_ALLOW_NOBODY)
            else:
                self.tr.write(_ALLOW)

    def _parse_one(self):
        """One complete request from self.buf, or None if incomplete.
        Malformed framing answers 400 and drops the connection (the
        stream position is unrecoverable)."""
        buf = self.buf
        end = buf.find(b"\r\n\r\n")
        if end < 0:
            if len(buf) > _MAX_HEAD:
                self._fail(b"header block too large\n")
            return None
        try:
            head = bytes(buf[:end]).split(b"\r\n")
            method, path, _version = head[0].split(b" ", 2)
            clen = 0
            group = b"0"
            mode = "local"
            session = 0
            token = None
            accept = b""
            kepoch = None
            deadline = None
            brownout = False
            for line in head[1:]:
                k, _, v = line.partition(b":")
                k = k.strip().lower()
                if k == b"content-length":
                    clen = int(v.strip())
                elif k == b"x-raft-group":
                    group = v.strip()
                elif k == b"x-consistency":
                    # Read mode: local (default) / session / follower
                    # / linear — README read-modes table.
                    mode = v.strip().lower().decode("latin-1") or "local"
                elif k == b"x-raft-session":
                    # Session watermark: the commit-watermark echo a
                    # previous response carried (read-your-writes).
                    session = int(v.strip() or 0)
                elif k == b"accept":
                    # /metrics content negotiation (Prometheus text).
                    accept = v.strip()
                elif k == b"x-raft-retry-token":
                    # Hex u64 retry token: pins the proposal's envelope
                    # id so client re-sends apply exactly once.
                    token = int(v.strip(), 16) & ((1 << 64) - 1)
                elif k == b"x-raft-keymap-epoch":
                    # Elastic keyspace: the mapping version the client
                    # routed by — the reshard plane fails closed on
                    # any mismatch (409 + the current keymap).
                    kepoch = int(v.strip())
                elif k == b"x-raft-deadline-ms":
                    # Overload plane: the client's REMAINING end-to-end
                    # budget for this attempt, in milliseconds.
                    deadline = float(v.strip())
                elif k == b"x-raft-brownout":
                    # Client consents to a session-read downgrade when
                    # the brownout ladder engages (never silent).
                    brownout = v.strip().lower() == b"allow"
        except (ValueError, IndexError):
            self._fail(b"malformed request\n")
            return None
        if not 0 <= clen <= _MAX_BODY:
            self._fail(b"bad content-length\n")
            return None
        total = end + 4 + clen
        if len(buf) < total:
            return None
        body = bytes(buf[end + 4:total])
        del buf[:total]
        return method, path, {"group": group, "mode": mode,
                              "session": session, "token": token,
                              "accept": accept.decode("latin-1"),
                              "kepoch": kepoch, "deadline": deadline,
                              "brownout": brownout}, body

    def _fail(self, msg: bytes) -> None:
        self.tr.write(_resp(400, b"Bad Request", msg))
        self.tr.close()
        self.closed = True

    # -- handlers (one in flight per connection) -----------------------

    def _finish(self, payload: bytes) -> None:
        if not self.closed:
            self.tr.write(payload)
        self.busy = False
        if self.buf and not self.closed:
            self._pump()

    def _shed_expired(self, deadline_ms) -> bool:
        """Edge shed (overload plane): a request whose budget is
        already spent does no consensus work — 504, counted shed_edge.
        Returns True when the request was answered here."""
        if deadline_ms is None or deadline_ms > 0:
            return False
        ov = getattr(self.srv.rdb.pipe.node, "overload", None)
        if ov is not None:
            ov.note_shed("edge")
        self._finish(_resp(504, b"Gateway Timeout",
                           b"deadline exceeded (edge)\n"))
        return True

    async def _do_put(self, headers: dict, body: bytes) -> None:
        rdb = self.srv.rdb
        try:
            query = body.decode("utf-8")
            group = int(headers["group"] or 0)
        except ValueError as e:
            self._finish(_resp(400, b"Bad Request",
                               (str(e) + "\n").encode()))
            return
        dl = headers["deadline"]
        if self._shed_expired(dl):
            return
        # The whole propose+await runs under the broad handling _do_get
        # uses: an unexpected exception (e.g. pipe/queue closed during
        # node shutdown) would otherwise kill this task and leave the
        # connection busy=True forever — the client hangs instead of
        # seeing a 400 (the threaded plane's do_PUT catches everything).
        fut = None
        try:
            fut = rdb.propose(query, group, token=headers["token"],
                              **({} if dl is None
                                 else {"deadline_ms": dl}))
            afut = self.srv.loop.create_future()
            fut.add_done_callback(
                lambda err: self.srv.bridge.deliver(afut, err))
            err = await asyncio.wait_for(
                afut, self.srv.timeout_s if dl is None
                else min(self.srv.timeout_s, dl / 1000.0))
        except asyncio.TimeoutError:
            # Deregister the ack so it cannot leak; the statement may
            # still commit later (api/http.py's abandon contract).
            rdb.abandon(query, group, fut)
            if dl is not None:
                ov = getattr(rdb.pipe.node, "overload", None)
                if ov is not None:
                    ov.note_shed("commit_wait")
            self._finish(_refusal_resp(
                TimeoutError("proposal not committed in time")))
            return
        except Overloaded as e:
            # Admission refusal: nothing was enqueued (rdb.propose
            # abandoned the ack) — 429 + jittered Retry-After.
            self._finish(_refusal_resp(e))
            return
        except NotLeaderError as e:
            # --pod owner refusal (server/main.py PodRaftDB), parity
            # with the threaded plane: 421 + X-Raft-Leader names the
            # owner host so the client chases instead of erroring.
            extra = ((b"X-Raft-Leader", str(e.leader).encode()),) \
                if e.leader > 0 else ()
            self._finish(_resp(421, b"Misdirected Request",
                               (str(e) + "\n").encode(), extra=extra))
            return
        except Exception as e:                      # noqa: BLE001
            log.info("client error: %s", e)
            if fut is not None:
                try:
                    rdb.abandon(query, group, fut)
                except Exception:                   # noqa: BLE001
                    pass
            self._finish(_resp(400, b"Bad Request",
                               (str(e) + "\n").encode()))
            return
        if err is not None:
            if isinstance(err, Overloaded):
                # Ring deployments surface admission refusals through
                # the ack path (RingFuture._err) — same 429 contract.
                self._finish(_refusal_resp(err))
                return
            log.info("client error: %s", err)
            self._finish(_resp(400, b"Bad Request",
                               (str(err) + "\n").encode()))
        else:
            # Commit-watermark echo (X-Raft-Session): the ack implies
            # local apply, so this watermark covers the write — a
            # session read presenting it gets read-your-writes at any
            # replica.
            extra = _session_extra(rdb, group)
            if extra:
                self._finish(b"HTTP/1.1 204 No Content\r\n"
                             + extra[0][0] + b": " + extra[0][1]
                             + b"\r\n\r\n")
            else:
                self._finish(_204)

    async def _do_members(self, body: bytes) -> None:
        """POST /members — membership admin write, parity with
        api/http.py: 200 + new config JSON, 421 + X-Raft-Leader at a
        non-leader, 400 on an illegal change."""
        import json as _json
        rdb = self.srv.rdb
        try:
            req = _json.loads(body.decode("utf-8") or "{}")
            got = await self.srv.loop.run_in_executor(
                self.srv._read_pool,
                lambda: rdb.member_change(int(req.get("group", 0)),
                                          str(req.get("op", "")),
                                          int(req.get("peer", -1))))
        except NotLeaderError as e:
            extra = ((b"X-Raft-Leader", str(e.leader).encode()),) \
                if e.leader > 0 else ()
            self._finish(_resp(421, b"Misdirected Request",
                               (str(e) + "\n").encode(), extra=extra))
            return
        except Exception as e:                      # noqa: BLE001
            log.info("client error: %s", e)
            self._finish(_resp(400, b"Bad Request",
                               (str(e) + "\n").encode()))
            return
        self._finish(_resp(200, b"OK",
                           (_json.dumps(got, sort_keys=True)
                            + "\n").encode(), b"application/json"))

    async def _do_transfer(self, body: bytes) -> None:
        """POST /transfer — graceful leadership transfer (thesis
        §3.10), parity with api/http.py: 200 + the armed-transfer JSON,
        421 + X-Raft-Leader at a non-leader, 400 on a refused request
        (in-flight transfer, learner target)."""
        import json as _json
        rdb = self.srv.rdb
        try:
            req = _json.loads(body.decode("utf-8") or "{}")
            got = await self.srv.loop.run_in_executor(
                self.srv._read_pool,
                lambda: rdb.transfer(int(req.get("group", 0)),
                                     int(req.get("target", -1))))
        except NotLeaderError as e:
            extra = ((b"X-Raft-Leader", str(e.leader).encode()),) \
                if e.leader > 0 else ()
            self._finish(_resp(421, b"Misdirected Request",
                               (str(e) + "\n").encode(), extra=extra))
            return
        except Exception as e:                      # noqa: BLE001
            log.info("client error: %s", e)
            self._finish(_resp(400, b"Bad Request",
                               (str(e) + "\n").encode()))
            return
        self._finish(_resp(200, b"OK",
                           (_json.dumps(got, sort_keys=True)
                            + "\n").encode(), b"application/json"))

    async def _do_reshard(self, body: bytes) -> None:
        """POST /reshard — enqueue an elastic-keyspace verb, parity
        with api/http.py: 200 + verb JSON, 409 while a verb is in
        flight, 503 with no plane compiled in."""
        import json as _json
        rdb = self.srv.rdb
        if rdb.reshard is None:
            self._finish(_resp(503, b"Service Unavailable",
                               b"no reshard plane (--reshard)\n"))
            return
        from raftsql_tpu.reshard.coordinator import ReshardRefused
        try:
            req = _json.loads(body.decode("utf-8") or "{}")
            got = rdb.reshard.enqueue(str(req.get("verb", "")),
                                      int(req.get("src", -1)),
                                      int(req.get("dst", -1)),
                                      req.get("slots"))
        except ReshardRefused as e:
            self._finish(_resp(409, b"Conflict",
                               (str(e) + "\n").encode()))
            return
        except Exception as e:                      # noqa: BLE001
            log.info("client error: %s", e)
            self._finish(_resp(400, b"Bad Request",
                               (str(e) + "\n").encode()))
            return
        self._finish(_resp(200, b"OK",
                           (_json.dumps(got, sort_keys=True)
                            + "\n").encode(), b"application/json"))

    async def _do_kv(self, method: bytes, path: bytes,
                     headers: dict, body: bytes) -> None:
        """PUT/GET /kv/<key> — the keyed elastic-keyspace surface.
        Responses pin X-Raft-Keymap-Epoch; a request routed by a stale
        epoch is refused with 409 + the current keymap document (fail
        closed — never silently served by a moved mapping)."""
        import json as _json
        rdb = self.srv.rdb
        plane = rdb.reshard
        if plane is None:
            self._finish(_resp(503, b"Service Unavailable",
                               b"no reshard plane (--reshard)\n"))
            return
        from raftsql_tpu.reshard.plane import FrozenSlot, WrongEpoch
        key = path[len(b"/kv/"):].decode("utf-8")

        def _epoch_extra():
            return ((b"X-Raft-Keymap-Epoch",
                     str(plane.keymap.epoch).encode()),)

        fut = None
        sql, group = "", 0
        dl = headers["deadline"]
        served: dict = {}
        try:
            if method == b"PUT":
                group, sql = plane.kv_put(key, body.decode("utf-8"),
                                          headers["kepoch"])
                if self._shed_expired(dl):
                    return
                fut = rdb.propose(sql, group, token=headers["token"],
                                  **({} if dl is None
                                     else {"deadline_ms": dl}))
                afut = self.srv.loop.create_future()
                fut.add_done_callback(
                    lambda err: self.srv.bridge.deliver(afut, err))
                err = await asyncio.wait_for(
                    afut, self.srv.timeout_s if dl is None
                    else min(self.srv.timeout_s, dl / 1000.0))
                if err is not None:
                    raise err
                extra = (_session_extra(rdb, group) + _epoch_extra())
                head = b"HTTP/1.1 204 No Content\r\n" + b"".join(
                    k + b": " + v + b"\r\n" for k, v in extra) + b"\r\n"
                self._finish(head)
                return
            group, sql = plane.kv_get(key, headers["kepoch"])
            if self._shed_expired(dl):
                return
            rows = await self.srv.loop.run_in_executor(
                self.srv._read_pool, lambda: rdb.query(
                    sql, group, timeout=self.srv.timeout_s,
                    mode=headers["mode"],
                    watermark=headers["session"],
                    deadline_ms=dl, brownout=headers["brownout"],
                    info=served))
        except WrongEpoch as e:
            payload = (_json.dumps(
                {"error": str(e), "keymap": plane.keymap.to_doc()},
                sort_keys=True) + "\n").encode()
            self._finish(_resp(409, b"Conflict", payload,
                               b"application/json",
                               extra=_epoch_extra()))
            return
        except FrozenSlot as e:
            # Retryable: the verb resolves and unfreezes the slot.
            self._finish(_refusal_resp(e))
            return
        except asyncio.TimeoutError:
            rdb.abandon(sql, group, fut)
            if dl is not None:
                ov = getattr(rdb.pipe.node, "overload", None)
                if ov is not None:
                    ov.note_shed("commit_wait")
            self._finish(_refusal_resp(
                TimeoutError("proposal not committed in time")))
            return
        except Overloaded as e:
            self._finish(_refusal_resp(e))
            return
        except NotLeaderError as e:
            extra = ((b"X-Raft-Leader", str(e.leader).encode()),) \
                if e.leader > 0 else ()
            self._finish(_resp(421, b"Misdirected Request",
                               (str(e) + "\n").encode(), extra=extra))
            return
        except TimeoutError as e:
            self._finish(_refusal_resp(e))
            return
        except Exception as e:                      # noqa: BLE001
            log.info("client error: %s", e)
            if fut is not None:
                try:
                    rdb.abandon(sql, group, fut)
                except Exception:                   # noqa: BLE001
                    pass
            self._finish(_resp(400, b"Bad Request",
                               (str(e) + "\n").encode()))
            return
        extra = _session_extra(rdb, group) + _epoch_extra()
        if served.get("served"):
            extra = extra + ((b"X-Raft-Served-Mode",
                              served["served"].encode()),)
        val = plane.kv_value(rows)
        if val is None:
            self._finish(_resp(404, b"Not Found", b"", extra=extra))
        else:
            self._finish(_resp(200, b"OK", val.encode("utf-8"),
                               extra=extra))

    async def _do_get(self, headers: dict, body: bytes) -> None:
        rdb = self.srv.rdb
        try:
            query = body.decode("utf-8")
            group = int(headers["group"] or 0)
        except ValueError as e:
            self._finish(_resp(400, b"Bad Request",
                               (str(e) + "\n").encode()))
            return
        dl = headers["deadline"]
        if self._shed_expired(dl):
            return
        served: dict = {}
        try:
            # Reads block (SQLite, and linear/session reads wait out a
            # quorum round or a watermark) — off the loop thread.
            rows = await self.srv.loop.run_in_executor(
                self.srv._read_pool, lambda: rdb.query(
                    query, group, timeout=self.srv.timeout_s,
                    mode=headers["mode"],
                    watermark=headers["session"],
                    deadline_ms=dl, brownout=headers["brownout"],
                    info=served))
        except Overloaded as e:
            # Admission refusal or brownout without opt-in: 429 +
            # jittered Retry-After — never a silent downgrade.
            self._finish(_refusal_resp(e))
            return
        except NotLeaderError as e:
            extra = ((b"X-Raft-Leader", str(e.leader).encode()),) \
                if e.leader > 0 else ()
            self._finish(_resp(421, b"Misdirected Request",
                               (str(e) + "\n").encode(), extra=extra))
            return
        except TimeoutError as e:
            self._finish(_refusal_resp(e))
            return
        except Exception as e:                      # noqa: BLE001
            log.info("client error: %s", e)
            self._finish(_resp(400, b"Bad Request",
                               (str(e) + "\n").encode()))
            return
        extra = _session_extra(rdb, group)
        if served.get("served"):
            # The brownout contract: the response always names the
            # mode it was actually served at.
            extra = extra + ((b"X-Raft-Served-Mode",
                              served["served"].encode()),)
        self._finish(_resp(200, b"OK", rows.encode("utf-8"),
                           extra=extra))


class AioSQLServer:
    """Drop-in alternative to api/http.py's SQLServer: same constructor
    shape, same start()/stop() lifecycle, one event-loop thread."""

    def __init__(self, port: int, rdb: RaftDB, host: str = "",
                 timeout_s: float = 30.0, reuse_port: bool = False):
        self.port = port
        self.rdb = rdb
        self.host = host
        self.timeout_s = timeout_s
        # SO_REUSEPORT: N worker processes bind the SAME port and the
        # kernel load-balances accepted connections across them — the
        # multi-worker serving plane (runtime/ring.py, --workers N).
        self.reuse_port = reuse_port
        self.loop: Optional[asyncio.AbstractEventLoop] = None
        self.bridge: Optional[_AckBridge] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()
        self._start_err: Optional[BaseException] = None
        self._server = None
        from concurrent.futures import ThreadPoolExecutor
        self._read_pool = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="aio-read")

    async def _serve(self) -> None:
        self.loop = asyncio.get_running_loop()
        self.bridge = _AckBridge(self.loop)
        self._server = await self.loop.create_server(
            lambda: _Conn(self), self.host or None, self.port,
            backlog=256, reuse_address=True,
            reuse_port=self.reuse_port or None)
        if self.port == 0:      # tests bind port 0 and read it back
            self.port = self._server.sockets[0].getsockname()[1]
        self._started.set()
        async with self._server:
            await self._server.serve_forever()

    def serve_forever(self) -> None:
        try:
            asyncio.run(self._serve())
        except asyncio.CancelledError:
            pass
        except BaseException as e:    # surface bind errors to start()
            self._start_err = e
            self._started.set()
            if threading.current_thread() is not self._thread:
                raise               # direct serve_forever() callers

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.serve_forever, daemon=True, name="aio-http")
        self._thread.start()
        if not self._started.wait(10):
            raise RuntimeError("aio http server failed to start")
        if self._start_err is not None:
            # The threaded SQLServer raises e.g. EADDRINUSE from its
            # constructor; re-raise the real cause here for parity.
            raise self._start_err

    def stop(self) -> None:
        loop = self.loop
        if loop is not None and loop.is_running():
            def _shutdown():
                for task in asyncio.all_tasks(loop):
                    task.cancel()
            try:
                loop.call_soon_threadsafe(_shutdown)
            except RuntimeError:  # pragma: no cover - already closed
                pass
        if self._thread is not None:
            self._thread.join(5)
        self._read_pool.shutdown(wait=False)
