from raftsql_tpu.core.state import (Inbox, Outbox, PeerState, StepInfo,
                                    empty_inbox, init_peer_state, tbl_floor,
                                    term_at, term_at_tbl)
from raftsql_tpu.core.step import peer_step, peer_step_jit

__all__ = ["Inbox", "Outbox", "PeerState", "StepInfo", "empty_inbox",
           "init_peer_state", "tbl_floor", "term_at", "term_at_tbl",
           "peer_step", "peer_step_jit"]
