from raftsql_tpu.core.state import (Inbox, Outbox, PeerState, StepInfo,
                                    empty_inbox, init_peer_state, term_at)
from raftsql_tpu.core.step import peer_step, peer_step_jit

__all__ = ["Inbox", "Outbox", "PeerState", "StepInfo", "empty_inbox",
           "init_peer_state", "term_at", "peer_step", "peer_step_jit"]
