"""Struct-of-arrays raft state for one peer across all groups.

The reference keeps one raft group's state inside the vendored etcd/raft
`raft.Node` object (reference raft.go:48-55).  The TPU-native design replaces
that object with flat int32 arrays batched over the group axis `G`, so that
the per-tick transition of *every* group advances in one XLA computation.

All log positions are 1-based: index 0 is the sentinel "before the log"
position with term 0 (this makes the AppendEntries log-matching check on
`prev_index == 0` fall out of ordinary array math).  The on-device log keeps
only entry *terms* in a ring of capacity W — entry payloads (SQL text) live
host-side in `storage.log`; the device decides ordering/commit, the host owns
bytes.
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from raftsql_tpu.config import (FOLLOWER, NO_LEADER, NO_VOTE, NO_XFER,
                                RaftConfig)

I32 = jnp.int32
B = jnp.bool_


class PeerState(NamedTuple):
    """Raft state of ONE peer, batched over G groups.

    Shapes:  [G] unless noted.  `match`/`next_idx`/`votes` are the leader /
    candidate views over the peer axis, [G, P].  `log_term` is the log
    metadata ring, [G, W].
    """

    term: jax.Array          # [G] i32 current term
    voted_for: jax.Array     # [G] i32 peer voted for this term, NO_VOTE if none
    role: jax.Array          # [G] i32 FOLLOWER/CANDIDATE/LEADER/PRECANDIDATE
    leader_hint: jax.Array   # [G] i32 last known leader, NO_LEADER if unknown

    commit: jax.Array        # [G] i32 highest committed log index
    log_len: jax.Array       # [G] i32 highest appended log index
    log_term: jax.Array      # [G, W] i32 ring: term of entry i at (i-1) % W

    # Term-transition table: the step's authoritative source for
    # term-of-position reads (the ring above stays write-only in the hot
    # path, serving the windowed/pallas commit rules and test oracles).
    # Slot k (valid iff tbl_pos[k] > 0) says: entries from position
    # tbl_pos[k] up to the next transition carry term tbl_term[k].  Valid
    # slots are right-aligned and ascending in position; slot K-1 always
    # holds the newest transition of a non-empty log.  Terms are known
    # for positions in [tbl_floor(tbl_pos, log_len), log_len]; reads
    # below the floor are guarded exactly like reads that slid out of
    # the W ring (reject + host catch-up).
    tbl_pos: jax.Array       # [G, K] i32 transition start positions
    tbl_term: jax.Array      # [G, K] i32 term starting at tbl_pos[k]

    # Timers (in ticks).
    elapsed: jax.Array       # [G] i32 ticks since last heartbeat/vote grant
    timeout: jax.Array       # [G] i32 randomized election timeout in ticks
    hb_elapsed: jax.Array    # [G] i32 leader ticks since last broadcast

    # Candidate view: votes granted to us this term.
    votes: jax.Array         # [G, P] bool

    # Leader view of each peer (raft Figure 2 volatile leader state).
    match: jax.Array         # [G, P] i32 highest index known replicated on peer
    next_idx: jax.Array      # [G, P] i32 next index to send to peer

    # Active membership configuration as DEVICE data (raftsql_tpu/
    # membership/): which of the P peer slots are voters, per group.
    # `voters_joint` is the OLD voter set while a joint C_old,new config
    # change is in flight (commit/election need a majority of BOTH
    # masks); in the stable state it equals `voters`, degenerating the
    # double-majority to the single one.  Slots outside both masks are
    # learners/spares: they receive AppendEntries and InstallSnapshot
    # but contribute nothing to any quorum and never campaign.  The
    # step only READS these; the host patches them (set_group_config)
    # when a committed conf-change entry applies.
    voters: jax.Array        # [G, P] bool
    voters_joint: jax.Array  # [G, P] bool

    # Leader-lease evidence (config.lease_ticks, core/step.py lease
    # phase): device step at which the newest CURRENT-term append
    # response from each peer was processed while this peer led the
    # group (0 = none).  Strictly an OUTPUT of consensus — no other
    # transition reads it, so carrying it (even disabled) can never
    # perturb a trajectory.  Deliberately volatile: a restart starts
    # from zeros, so a rebooted leader holds no lease until a fresh
    # quorum round confirms it.
    resp_tick: jax.Array     # [G, P] i32

    # Leadership-transfer latch (raft thesis §3.10, core/step.py transfer
    # phase): peer slot this group's LEADER row is transferring to, or
    # NO_XFER.  While set on a leader row the group stops accepting new
    # proposals and re-sends MSG_TIMEONOW to the target each tick once
    # its match has caught up; the row auto-clears the moment the row is
    # no longer leader (deposed by the target's election — completion —
    # or by anyone else).  The host patches it (set_transfer_target) and
    # owns deadline/abort; with every row at NO_XFER the whole phase is
    # gate-false and trajectories are bit-identical to the pre-transfer
    # kernel.  Volatile across restart by design (init gives NO_XFER):
    # a rebooted leader holds no transfer.
    xfer_target: jax.Array   # [G] i32

    rng: jax.Array           # [2]/key PRNG state for election jitter
    tick: jax.Array          # [] i32 step counter (for PRNG folding)


class Inbox(NamedTuple):
    """Dense per-source message slots delivered to one peer.

    Two slots per (group, source): a *vote* slot (RequestVote / PreVote
    req/resp) and an *append* slot (AppendEntries req/resp), distinguished
    by type codes MSG_NONE / MSG_REQ / MSG_RESP, plus — vote slot only —
    MSG_PREREQ / MSG_PRERESP.  This replaces the vendored etcd
    `raftpb.Message` stream (reference raft.go:268-270) with fixed-width
    arrays that map directly onto device memory.

    Overwrite-newest slot semantics are safe: raft tolerates message loss,
    and leaders/candidates re-send every heartbeat tick.
    """

    # Vote slot [G, P]:
    v_type: jax.Array        # i32 MSG_NONE/MSG_REQ/MSG_RESP/MSG_PREREQ/MSG_PRERESP
    v_term: jax.Array        # i32 sender term
    v_last_idx: jax.Array    # i32 (req) candidate last log index
    v_last_term: jax.Array   # i32 (req) candidate last log term
    v_granted: jax.Array     # bool (resp) vote granted

    # Append slot [G, P] (+ [G, P, E] entry terms):
    a_type: jax.Array        # i32 MSG_NONE / MSG_REQ / MSG_RESP
    a_term: jax.Array        # i32 sender term
    a_prev_idx: jax.Array    # i32 (req) index preceding the batch
    a_prev_term: jax.Array   # i32 (req) term of prev_idx
    a_n: jax.Array           # i32 (req) number of entries in batch
    a_ents: jax.Array        # [G, P, E] i32 (req) terms of batch entries
    a_commit: jax.Array      # i32 (req) leader commit index
    a_success: jax.Array     # bool (resp) append accepted
    a_match: jax.Array       # i32 (resp) match index (or conflict hint)


# The outbox has the same schema, indexed [G, dst] instead of [G, src].
Outbox = Inbox


class StepInfo(NamedTuple):
    """Host-facing observations from one step (all [G] unless noted).

    These drive the host side of the durability contract (reference
    raft.go:227-235): WAL save of HardState {term, voted_for, commit} and of
    newly appended entries, payload-log mirroring, and apply-at-commit.
    """

    commit: jax.Array        # i32 commit index after the step
    role: jax.Array          # i32 role after the step
    term: jax.Array          # i32 term after the step
    voted_for: jax.Array     # i32 vote cast this term (WAL HardState)
    leader_hint: jax.Array   # i32 current leader if known
    prop_base: jax.Array     # i32 log index before accepted proposals
    prop_accepted: jax.Array  # i32 number of proposals appended this step
    noop: jax.Array          # bool leader appended a no-op at prop_base
    # Host log-mirroring signals for inbound appends (see step.py):
    app_from: jax.Array      # i32 src peer whose append we accepted, -1 none
    app_start: jax.Array     # i32 first log index written from that append
    app_n: jax.Array         # i32 number of entries written
    app_conflict: jax.Array  # bool append truncated conflicting suffix
    new_log_len: jax.Array   # i32 log length after the step
    # Leader-lease expiry in device-step units (0 = no lease): while
    # `host_step_now + cfg.max_clock_skew < lease`, this peer may serve
    # group g a linearizable read at its current commit index without a
    # quorum round (core/step.py lease phase; always 0 when
    # cfg.lease_ticks == 0).  The §6.4 current-term-commit
    # precondition is already folded in on device.
    lease: jax.Array         # i32 [G]
    # Leadership-transfer latch AFTER the step (PeerState.xfer_target
    # carry): the target while this row still leads and a transfer is
    # armed, NO_XFER otherwise.  The host watches this column drop back
    # to NO_XFER on the (former) leader row to detect completion — the
    # device clears it the tick the row is deposed.
    xfer: jax.Array          # i32 [G]
    # Leader view [G, P]: where each peer's replication stands.  The host
    # uses this to spot followers that have fallen out of the device term
    # ring (next_idx <= log_len - W) OR below the transition-table floor
    # and feed them catch-up appends built from the host payload log
    # (runtime/node.py).
    next_idx: jax.Array      # i32 [G, P] next index to send each peer
    # Lowest position whose term the transition table still knows
    # (core/step.py floor1): the device suppresses real appends to
    # followers below it, so the host must serve them.
    floor: jax.Array         # i32 [G]
    # Scalar i32: minimum timer ticks (across all groups) until ANY
    # election or heartbeat timer could fire, given no inbound messages.
    # The host's event loop skips whole steps while its accumulated
    # timer advance stays below this margin and nothing is staged
    # (runtime/node.py _run) — an idle node costs ~zero CPU between
    # heartbeats instead of a full step per tick interval.
    timer_margin: jax.Array  # i32 []


def init_peer_state(cfg: RaftConfig, self_id: int | jax.Array,
                    seed: int | None = None) -> PeerState:
    """Fresh boot state (empty log, term 0, follower everywhere).

    Election timeouts start randomized per group/peer so that a cold-booted
    cluster doesn't produce a split vote storm in lockstep — the moral
    equivalent of etcd/raft's randomized election timer.
    """
    g, p, w = cfg.num_groups, cfg.num_peers, cfg.log_window
    seed = cfg.seed if seed is None else seed
    key = jax.random.fold_in(jax.random.PRNGKey(seed), jnp.asarray(self_id))
    key, sub = jax.random.split(key)
    timeout = jax.random.randint(
        sub, (g,), cfg.election_ticks, 2 * cfg.election_ticks, dtype=I32)
    voters = jnp.broadcast_to(
        jnp.asarray(initial_voter_row(cfg))[None, :], (g, p))
    # Distinct buffer, not an alias: the two masks are donated together
    # by the jitted step, and a shared buffer trips double-donation.
    voters_joint = jnp.array(voters)
    return PeerState(
        term=jnp.zeros((g,), I32),
        voted_for=jnp.full((g,), NO_VOTE, I32),
        role=jnp.full((g,), FOLLOWER, I32),
        leader_hint=jnp.full((g,), NO_LEADER, I32),
        commit=jnp.zeros((g,), I32),
        log_len=jnp.zeros((g,), I32),
        log_term=jnp.zeros((g, w if cfg.keep_ring else 1), I32),
        tbl_pos=jnp.zeros((g, cfg.term_table_slots), I32),
        tbl_term=jnp.zeros((g, cfg.term_table_slots), I32),
        elapsed=jnp.zeros((g,), I32),
        timeout=timeout,
        hb_elapsed=jnp.zeros((g,), I32),
        votes=jnp.zeros((g, p), B),
        match=jnp.zeros((g, p), I32),
        next_idx=jnp.ones((g, p), I32),
        voters=voters,
        voters_joint=voters_joint,
        resp_tick=jnp.zeros((g, p), I32),
        xfer_target=jnp.full((g,), NO_XFER, I32),
        rng=key,
        tick=jnp.zeros((), I32),
    )


def initial_voter_row(cfg: RaftConfig):
    """[P] bool numpy row of cfg's boot-time voter set (all True when
    cfg.initial_voters is None — the static-cluster default)."""
    import numpy as np

    p = cfg.num_peers
    if cfg.initial_voters is None:
        return np.ones((p,), bool)
    row = np.zeros((p,), bool)
    row[list(cfg.initial_voters)] = True
    return row


def witness_row(cfg: RaftConfig):
    """[P] bool numpy row of cfg's witness slots (all False by default).

    Witness identity is STATIC per config — a compiled constant the
    step indexes with its traced self_id (cluster.py vmaps self_ids),
    never device state: witnesses are a deployment shape, not something
    a log entry changes mid-flight."""
    import numpy as np

    row = np.zeros((cfg.num_peers,), bool)
    if cfg.witnesses:
        row[list(cfg.witnesses)] = True
    return row


@functools.partial(jax.jit, donate_argnums=0)
def set_group_config(state: PeerState, g: jax.Array,
                     voters_row: jax.Array, joint_row: jax.Array,
                     self_is_voter: jax.Array) -> PeerState:
    """Patch group `g`'s active configuration into the device masks.

    Called by the host membership plane when a conf-change log entry
    APPLIES at commit (two-phase joint style: C_old,new sets voters=new
    + voters_joint=old; C_new sets both to new).  `self_is_voter` is
    whether THIS peer remains a voter under the new config: a leader
    removed by the change steps down to follower on apply (raft §6 —
    it led long enough to commit its own removal), and a demoted slot
    can never campaign again (core/step.py gates election timeouts on
    the mask)."""
    g = jnp.asarray(g, I32)
    vrow = jnp.asarray(voters_row, B)
    jrow = jnp.asarray(joint_row, B)
    # A non-voter must not be (or stay) leader/candidate: drop to
    # follower and clear its tally.  It keeps replicating as a learner;
    # the next append teaches it the new leader.
    demote = ~jnp.asarray(self_is_voter, B) & (state.role[g] != FOLLOWER)
    return state._replace(
        voters=state.voters.at[g].set(vrow),
        voters_joint=state.voters_joint.at[g].set(jrow),
        role=state.role.at[g].set(
            jnp.where(demote, FOLLOWER, state.role[g])),
        leader_hint=state.leader_hint.at[g].set(
            jnp.where(demote, NO_LEADER, state.leader_hint[g])),
        votes=state.votes.at[g].set(
            jnp.where(demote, False, state.votes[g])),
    )


def restore_peer_state(cfg: RaftConfig, self_id: int,
                       log_terms: dict, hard: dict,
                       seed: int | None = None,
                       starts: dict | None = None) -> PeerState:
    """Rebuild boot state from a replayed WAL (the reference's RestartNode
    path, raft.go:122-134, 161-163).

    Args:
      log_terms: {group: [term of entry start+1, start+2, ...]}
      hard: {group: (term, voted_for, commit)}
      starts: {group: (start, start_term)} — WAL-compaction floors; the
        prefix up to `start` is snapshot-covered (committed + applied),
        entries list begins at start+1.  The boundary term is seeded into
        the ring so prev-term checks at the edge resolve on device.
    """
    import numpy as np

    st = init_peer_state(cfg, self_id, seed)
    g_, k_ = cfg.num_groups, cfg.term_table_slots
    # Honor the keep_ring stub contract: the restored pytree must have
    # the same leaf shapes as init_peer_state's, or the post-restart jit
    # programs (and sharded buffers) would retrace against a wide ring
    # the config promised not to carry.
    w = cfg.log_window if cfg.keep_ring else 1
    starts = starts or {}
    term = np.zeros((g_,), np.int32)
    voted = np.full((g_,), NO_VOTE, np.int32)
    commit = np.zeros((g_,), np.int32)
    log_len = np.zeros((g_,), np.int32)
    window = np.zeros((g_, w), np.int32)
    tbl_pos = np.zeros((g_, k_), np.int32)
    tbl_term = np.zeros((g_, k_), np.int32)
    for g in range(g_):
        t, v, c = hard.get(g, (0, NO_VOTE, 0))
        term[g], voted[g], commit[g] = t, v, c
        start, start_term = starts.get(g, (0, 0))
        terms = log_terms.get(g, [])
        log_len[g] = start + len(terms)
        lo = max(start + 1, log_len[g] - w + 1)
        for idx in range(lo, log_len[g] + 1):
            window[g, (idx - 1) % w] = terms[idx - 1 - start]
        if start >= 1 and start > log_len[g] - w:
            window[g, (start - 1) % w] = start_term
        # Term-transition table over the same known span: the boundary
        # (start, start_term) if still adjacent, then every term change
        # in the replayed entries; keep the newest K, right-aligned.
        trans = []
        if start >= 1:
            trans.append((start, start_term))
        last = start_term if start >= 1 else 0
        for idx in range(start + 1, log_len[g] + 1):
            tt = terms[idx - 1 - start]
            if tt != last:
                trans.append((idx, tt))
                last = tt
        trans = trans[-k_:]
        for j, (pos_, term_) in enumerate(trans):
            tbl_pos[g, k_ - len(trans) + j] = pos_
            tbl_term[g, k_ - len(trans) + j] = term_
        # The snapshot floor is committed by construction; hard.commit can
        # trail it only if the marker postdates the last hardstate record.
        commit[g] = min(max(commit[g], start), log_len[g])
    return st._replace(
        term=jnp.asarray(term), voted_for=jnp.asarray(voted),
        commit=jnp.asarray(commit), log_len=jnp.asarray(log_len),
        log_term=jnp.asarray(window),
        tbl_pos=jnp.asarray(tbl_pos), tbl_term=jnp.asarray(tbl_term))


@functools.partial(jax.jit, donate_argnums=0)
def set_group_config_stacked(states: PeerState, p: jax.Array,
                             g: jax.Array, voters_row: jax.Array,
                             joint_row: jax.Array,
                             self_is_voter: jax.Array) -> PeerState:
    """`set_group_config` over a STACKED cluster state (leaves
    [P, G, ...], runtime/fused.py): patch peer row `p`'s view of group
    `g`.  Each peer row applies a conf entry when ITS OWN commit passes
    the entry — exactly the distributed runtime's timing, co-located."""
    p = jnp.asarray(p, I32)
    g = jnp.asarray(g, I32)
    vrow = jnp.asarray(voters_row, B)
    jrow = jnp.asarray(joint_row, B)
    demote = ~jnp.asarray(self_is_voter, B) \
        & (states.role[p, g] != FOLLOWER)
    return states._replace(
        voters=states.voters.at[p, g].set(vrow),
        voters_joint=states.voters_joint.at[p, g].set(jrow),
        role=states.role.at[p, g].set(
            jnp.where(demote, FOLLOWER, states.role[p, g])),
        leader_hint=states.leader_hint.at[p, g].set(
            jnp.where(demote, NO_LEADER, states.leader_hint[p, g])),
        votes=states.votes.at[p, g].set(
            jnp.where(demote, False, states.votes[p, g])),
    )


@functools.partial(jax.jit, donate_argnums=0)
def set_transfer_target(state: PeerState, g: jax.Array,
                        target: jax.Array) -> PeerState:
    """Arm (target >= 0) or clear (NO_XFER) group `g`'s leadership
    transfer on this peer's row.  Host-plane admin patch, same contract
    as set_group_config: the step only READS xfer_target; arming a
    non-leader row is harmless (the step clears it next tick), and the
    abort path clears it to cleanly re-open the group for proposals."""
    g = jnp.asarray(g, I32)
    return state._replace(
        xfer_target=state.xfer_target.at[g].set(jnp.asarray(target, I32)))


@functools.partial(jax.jit, donate_argnums=0)
def set_transfer_target_stacked(states: PeerState, p: jax.Array,
                                g: jax.Array,
                                target: jax.Array) -> PeerState:
    """`set_transfer_target` over a STACKED cluster state (leaves
    [P, G, ...], runtime/fused.py / runtime/mesh.py): arm or clear peer
    row `p`'s transfer latch for group `g`."""
    p = jnp.asarray(p, I32)
    g = jnp.asarray(g, I32)
    return states._replace(
        xfer_target=states.xfer_target.at[p, g].set(
            jnp.asarray(target, I32)))


@functools.partial(jax.jit, donate_argnums=0)
def install_snapshot_state(state: PeerState, g: jax.Array,
                           last_idx: jax.Array, last_term: jax.Array,
                           sender_term: jax.Array) -> PeerState:
    """Reset group `g`'s device row to a snapshot boundary.

    The follower installed a state-machine image at log position
    `last_idx` (entry term `last_term`): its log becomes exactly that
    prefix — length and commit jump to last_idx, the term ring is cleared
    except the boundary slot, and the row drops to follower so normal
    replication resumes from last_idx + 1 (raft §7 InstallSnapshot; no
    analog in the reference, which never snapshots, db.go:27-29).

    `sender_term` is the sending leader's term: a higher term is adopted
    (vote cleared), exactly as any raft RPC with term > currentTerm.  The
    caller must have already rejected sender_term < currentTerm — this
    function cannot, because the install itself (log/commit jump) must
    not happen for stale senders.
    """
    g = jnp.asarray(g, I32)
    last_idx = jnp.asarray(last_idx, I32)
    # The ring may be a [G, 1] stub (cfg.keep_ring=False): write modulo
    # its actual width, which degenerates harmlessly.
    rw = state.log_term.shape[-1]
    ring = jnp.zeros((rw,), I32).at[(last_idx - 1) % rw].set(
        jnp.asarray(last_term, I32))
    # Table analog of the cleared ring: one transition at the snapshot
    # boundary — terms known exactly for [last_idx, last_idx].
    K = state.tbl_pos.shape[-1]
    tpos = jnp.zeros((K,), I32).at[K - 1].set(last_idx)
    tterm = jnp.zeros((K,), I32).at[K - 1].set(jnp.asarray(last_term, I32))
    sender_term = jnp.asarray(sender_term, I32)
    newer = sender_term > state.term[g]
    return state._replace(
        term=state.term.at[g].set(jnp.maximum(state.term[g], sender_term)),
        voted_for=state.voted_for.at[g].set(
            jnp.where(newer, NO_VOTE, state.voted_for[g])),
        log_len=state.log_len.at[g].set(last_idx),
        commit=state.commit.at[g].set(last_idx),
        log_term=state.log_term.at[g].set(ring),
        tbl_pos=state.tbl_pos.at[g].set(tpos),
        tbl_term=state.tbl_term.at[g].set(tterm),
        role=state.role.at[g].set(FOLLOWER),
        votes=state.votes.at[g].set(False),
        match=state.match.at[g].set(0),
        next_idx=state.next_idx.at[g].set(last_idx + 1),
        elapsed=state.elapsed.at[g].set(0),
        resp_tick=state.resp_tick.at[g].set(0),
        xfer_target=state.xfer_target.at[g].set(NO_XFER),
    )


@functools.partial(jax.jit, donate_argnums=0)
def set_peer_progress(state: PeerState, g: jax.Array, d: jax.Array,
                      next_idx: jax.Array) -> PeerState:
    """Leader-side optimistic advance after shipping a snapshot to peer
    `d`: replication resumes at next_idx = last_idx + 1.  `match` is NOT
    touched: the step clamps next_idx to match+1 from below, so a match
    the peer never acknowledged would block reject-walkback permanently —
    a snapshot sent to a dead peer would strand it.  If the transfer is
    lost, the peer's rejects walk next_idx back and retrigger it; if it
    lands, the next real append's ack advances match."""
    g = jnp.asarray(g, I32)
    d = jnp.asarray(d, I32)
    return state._replace(
        next_idx=state.next_idx.at[g, d].set(jnp.asarray(next_idx, I32)))


def empty_inbox(cfg: RaftConfig) -> Inbox:
    g, p, e = cfg.num_groups, cfg.num_peers, cfg.max_entries_per_msg
    z = jnp.zeros((g, p), I32)
    zb = jnp.zeros((g, p), B)
    return Inbox(
        v_type=z, v_term=z, v_last_idx=z, v_last_term=z, v_granted=zb,
        a_type=z, a_term=z, a_prev_idx=z, a_prev_term=z, a_n=z,
        a_ents=jnp.zeros((g, p, e), I32), a_commit=z,
        a_success=zb, a_match=z,
    )


def term_at_tbl(tbl_pos: jax.Array, tbl_term: jax.Array, log_len: jax.Array,
                idx: jax.Array) -> jax.Array:
    """Term of entry `idx` from the transition table; term_at(0) == 0.

    `idx` may be [...] or [..., X] against tables [..., K].  Because terms
    are nondecreasing in position, the term at idx is the MAX term over
    valid transitions starting at or before idx.  Out of range (idx < 1,
    idx > log_len, or idx below the table floor) returns 0 — callers
    guard floor reads exactly as they guard out-of-ring reads.

    This is the O(K) read that replaced the O(W) ring read in the hot
    step: the [G, P, E] batch-term read alone was 68% of the profiled
    TPU tick at G=32k (see ops/dense.py for why gathers are not an
    option on that backend).
    """
    idx = jnp.asarray(idx)
    squeeze = idx.ndim == tbl_pos.ndim - 1
    idx2 = idx[..., None] if squeeze else idx
    hit = (tbl_pos[..., None, :] > 0) \
        & (tbl_pos[..., None, :] <= idx2[..., None])    # [..., X, K]
    got = jnp.max(jnp.where(hit, tbl_term[..., None, :], 0), axis=-1)
    if squeeze:
        got = got[..., 0]
    else:
        log_len = log_len[..., None]
    valid = (idx >= 1) & (idx <= log_len)
    return jnp.where(valid, got, 0)


def tbl_floor(tbl_pos: jax.Array, log_len: jax.Array) -> jax.Array:
    """Lowest position whose term the table still knows; log_len + 1 for
    an empty table (every read is then out of range anyway)."""
    valid = tbl_pos > 0
    f = jnp.min(jnp.where(valid, tbl_pos, jnp.iinfo(I32).max), axis=-1)
    return jnp.where(valid.any(-1), f, log_len + 1)


def term_at(log_term: jax.Array, log_len: jax.Array, idx: jax.Array,
            window: int) -> jax.Array:
    """Term of entry `idx` from the ring, with term_at(0) == 0.

    `idx` may be [G] or [G, P]-shaped (log arrays broadcast accordingly).
    Out-of-range (idx < 1 or idx > log_len) returns 0.  Positions that have
    slid out of the ring return whatever was overwritten — the host flow
    controller guarantees the engine never asks for those (see
    runtime/node.py flow control and config.log_window).
    """
    # ops.dense.take_last: on TPU this lowers to a fused one-hot
    # select-reduce instead of an XLA gather (which serializes per index
    # on that backend — see ops/dense.py).
    from raftsql_tpu.ops.dense import take_last

    idx = jnp.asarray(idx)
    squeeze = idx.ndim == log_term.ndim - 1
    idx2 = idx[..., None] if squeeze else idx
    got = take_last(log_term, (idx2 - 1) % window)
    if squeeze:
        got = got[..., 0]
    else:
        log_len = log_len[..., None]
    valid = (idx >= 1) & (idx <= log_len)
    return jnp.where(valid, got, 0)
