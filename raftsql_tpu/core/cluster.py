"""Fused whole-cluster stepping: P peers × G groups in one device program.

The reference runs each raft peer as a separate OS process wired by HTTP
streams (reference raft.go:248-266, Procfile).  On TPU, when a cluster's
peers are co-located on one chip (the benchmark configuration in
BASELINE.json), we instead *stack* all P peers' states on the leading axis,
vmap the peer transition over it, and deliver messages by transposing the
outbox — src→dst becomes dst→src with a single `swapaxes`, entirely
on-device.  Consensus for the whole cluster then advances via `lax.scan`
with zero host round-trips per tick.

The same `peer_step` also serves the distributed deployment (one PeerState
per host, transport carrying outboxes over DCN) — see runtime/node.py and
transport/.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from raftsql_tpu.config import RaftConfig
from raftsql_tpu.core.state import (I32, Inbox, Outbox, PeerState, StepInfo,
                                    empty_inbox, init_peer_state)
from raftsql_tpu.core.step import pack_info, peer_step


def init_cluster_state(cfg: RaftConfig, seed: int | None = None) -> PeerState:
    """Stacked PeerState with a leading peers axis: every leaf [P, ...]."""
    states = [init_peer_state(cfg, p, seed) for p in range(cfg.num_peers)]
    return jax.tree.map(lambda *xs: jnp.stack(xs), *states)


def empty_cluster_inbox(cfg: RaftConfig) -> Inbox:
    boxes = [empty_inbox(cfg) for _ in range(cfg.num_peers)]
    return jax.tree.map(lambda *xs: jnp.stack(xs), *boxes)


def deliver(outbox: Outbox) -> Inbox:
    """In-device message delivery: [src, G, dst, ...] -> [dst, G, src, ...].

    This transpose is the entire transport for co-located peers — the moral
    equivalent of the reference's rafthttp streams (raft.go:230, 268-270)
    collapsing into a data-layout change.  On a multi-chip mesh with the
    peer axis sharded, the same operation becomes an `all_to_all` over ICI
    (see parallel/sharded.py).
    """
    return jax.tree.map(lambda x: jnp.swapaxes(x, 0, 2), outbox)


def cluster_step(cfg: RaftConfig, states: PeerState, inboxes: Inbox,
                 prop_n: jax.Array, timer_inc: jax.Array | int = 1
                 ) -> Tuple[PeerState, Inbox, StepInfo]:
    """One tick for the whole co-located cluster.

    Args:
      states: stacked PeerState, leaves [P, ...].
      inboxes: stacked Inbox, leaves [P, G, P, ...].
      prop_n: [P, G] i32 — proposals submitted at each peer this tick (only
        the leader's are accepted; host routes via leader_hint).
      timer_inc: scalar (lockstep) or [P] i32 — PER-PEER election/
        heartbeat timer advance this step.  Real deployments never tick
        in lockstep; a [P] vector lets peers drift (chaos clock-skew
        schedules, and any future per-peer pacing).  Each peer's scalar
        reaches core/step.py's timer_inc unchanged, so timer semantics
        per peer are identical to the distributed runtime's.

    Returns:
      (new_states, delivered_inboxes_for_next_tick, stacked_infos).
    """
    self_ids = jnp.arange(cfg.num_peers, dtype=I32)
    ti = jnp.broadcast_to(jnp.asarray(timer_inc, I32), (cfg.num_peers,))

    def _one(st, ib, pn, sid, t):
        return peer_step(cfg, st, ib, pn, sid, timer_inc=t)

    new_states, outboxes, infos = jax.vmap(_one)(states, inboxes, prop_n,
                                                 self_ids, ti)
    return new_states, deliver(outboxes), infos


@functools.partial(jax.jit, static_argnums=0, donate_argnums=(1, 2))
def cluster_step_jit(cfg: RaftConfig, states: PeerState, inboxes: Inbox,
                     prop_n: jax.Array, timer_inc: jax.Array | int = 1):
    return cluster_step(cfg, states, inboxes, prop_n, timer_inc)


@functools.partial(jax.jit, static_argnums=0, donate_argnums=(1, 2))
def cluster_step_host(cfg: RaftConfig, states: PeerState, inboxes: Inbox,
                      prop_n: jax.Array, timer_inc: jax.Array | int = 1):
    """Fused step for the DURABLE co-located runtime (runtime/fused.py):
    messages stay on device (the delivered inboxes are returned as
    opaque carry), and the host-facing StepInfo crosses as ONE packed
    [P, G, INFO_NCOLS] array (core/step.py pack_info) — the host pays a
    single transfer per tick however many peers and groups advance.

    The extra scalar `busy` reports device-only protocol work the
    packed info cannot show — vote traffic, entry-carrying appends, and
    REJECTED append responses (a post-restart log-reconciliation walk
    is nothing but probe/reject rounds with zero host-visible effect).
    The runtime's idle-parking loop must keep full pace while it is
    set; steady-state heartbeats (empty REQ, successful RESP) do not
    count, so a settled cluster still parks."""
    from raftsql_tpu.config import MSG_REQ, MSG_RESP

    st, ib, infos = cluster_step(cfg, states, inboxes, prop_n, timer_inc)
    busy = (jnp.any(ib.v_type != 0)
            | jnp.any((ib.a_type == MSG_REQ) & (ib.a_n > 0))
            | jnp.any((ib.a_type == MSG_RESP) & ~ib.a_success))
    return st, ib, jax.vmap(pack_info)(infos), busy


@functools.partial(jax.jit, static_argnums=(0, 3), donate_argnums=(1, 2))
def cluster_multistep_host(cfg: RaftConfig, states: PeerState,
                           inboxes: Inbox, steps: int, prop_n: jax.Array,
                           timer_inc: jax.Array | int = 1):
    """`steps` fused steps in ONE dispatch, for the co-located durable
    runtime (runtime/fused.py steps_per_dispatch): device dispatch
    overhead — the dominant per-tick cost through a remote-device
    tunnel — is paid once per S consensus steps instead of once per
    step, and a proposal entering at the dispatch boundary commits
    INSIDE the dispatch (the 3-step pipeline runs to completion before
    the host's durable barrier).

    Safe for the single-process cluster only: intra-dispatch message
    exchange is not individually durable, which is sound there because
    the process is the failure domain — a crash loses every peer at
    once and replay rebuilds from the WALs the host wrote (all S steps'
    appends + the final hard state) before anything was published.

    Proposals arrive PER STEP (`prop_n` is [S, P, G] — the host chunks
    its backlog ≤E per step, so one dispatch accepts and commits up to
    S×E per group); packed host-facing info returns PER STEP, stacked
    [S, P, G, C], so the host replays its durable phases in step
    order.  busy is OR-reduced across steps."""
    from raftsql_tpu.config import MSG_REQ, MSG_RESP

    def body(carry, prop_t):
        st, ib = carry
        st, ib, info = cluster_step(cfg, st, ib, prop_t, timer_inc)
        busy_s = (jnp.any(ib.v_type != 0)
                  | jnp.any((ib.a_type == MSG_REQ) & (ib.a_n > 0))
                  | jnp.any((ib.a_type == MSG_RESP) & ~ib.a_success))
        return (st, ib), (jax.vmap(pack_info)(info), busy_s)

    (states, inboxes), (pinfos, busys) = jax.lax.scan(
        body, (states, inboxes), prop_n, length=steps)
    return states, inboxes, pinfos, jnp.any(busys)


@functools.partial(jax.jit, static_argnums=(0, 3), donate_argnums=(1, 2))
def cluster_run(cfg: RaftConfig, states: PeerState, inboxes: Inbox,
                num_ticks: int, prop_n: jax.Array
                ) -> Tuple[PeerState, Inbox, StepInfo]:
    """Scan `num_ticks` fused steps on device; prop_n is [T, P, G].

    Returns the final state plus per-tick stacked infos [T, P, G] — the
    benchmark harness reduces those on device to commit counts so only
    scalars cross the host boundary.
    """

    def body(carry, prop_t):
        st, ib = carry
        st, ib, info = cluster_step(cfg, st, ib, prop_t)
        return (st, ib), info

    (states, inboxes), infos = jax.lax.scan(body, (states, inboxes), prop_n,
                                            length=num_ticks)
    return states, inboxes, infos
