"""The batched raft peer transition — one tick for G groups in one XLA program.

This replaces the vendored etcd/raft state machine the reference drives via
`Tick`/`Propose`/`Ready`/`Advance` (reference raft.go:204-245): leader
election, log replication, and quorum commit are expressed as masked dense
int ops over `[G]` / `[G, P]` / `[G, W]` arrays, so one `peer_step` advances
every raft group owned by this peer at once.

Semantics follow the raft paper (Figure 2) plus two etcd-isms the reference
relies on:
  * randomized election timeouts (per group, per peer);
  - a no-op entry appended by a freshly elected leader, so old-term entries
    commit without waiting for client traffic (the reference inherits this
    from etcd/raft; its publish loop skips the empty entries,
    reference raft.go:84-87).

Design notes (TPU-first):
  - No data-dependent control flow: every branch is a `jnp.where` over all
    groups.  Inactive groups cost lanes, not branches.
  - Messages are fixed-slot dense arrays (one vote slot + one append slot
    per (group, src)); overwrite-newest is safe because raft tolerates loss
    and senders re-send every heartbeat tick.
  - The log keeps only terms on device, in a ring of capacity W; payload
    bytes stay host-side.  Flow control (runtime/node.py) keeps the ring
    from overrunning — the analog of the reference's MaxInflightMsgs window
    (reference raft.go:158).
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from raftsql_tpu.config import (CANDIDATE, FLOOR_HINT_BIAS, FOLLOWER, LEADER,
                                MSG_NONE, MSG_PREREQ, MSG_PRERESP, MSG_REQ,
                                MSG_RESP, MSG_TIMEONOW, NO_LEADER, NO_VOTE,
                                NO_XFER, PRECANDIDATE, RaftConfig)
from raftsql_tpu.core.state import (I32, Inbox, Outbox, PeerState, StepInfo,
                                    tbl_floor, term_at_tbl, witness_row)
from raftsql_tpu.ops import dense
from raftsql_tpu.ops.quorum import (masked_quorum_commit_index,
                                    masked_quorum_match_index,
                                    masked_vote_win, quorum_commit_index,
                                    quorum_match_index, vote_count)


def peer_step(cfg: RaftConfig, state: PeerState, inbox: Inbox,
              prop_n: jax.Array, self_id: jax.Array,
              group_offset: jax.Array | int = 0,
              timer_inc: jax.Array | int = 1,
              force_bcast: jax.Array | bool = False
              ) -> Tuple[PeerState, Outbox, StepInfo]:
    """Advance one peer's view of all G groups by one tick.

    Args:
      cfg: static configuration (shapes, timeouts).
      state: this peer's PeerState.
      inbox: dense message slots received since the last tick.
      prop_n: [G] i32 — number of new local proposals to append if leader
        (capped at cfg.max_entries_per_msg; host queues the rest).
      self_id: scalar i32 — this peer's 0-based id (traced, so the same
        compiled program serves every peer and vmaps over the peer axis).
      group_offset: scalar i32 — global id of group row 0.  Election
        jitter is drawn per GLOBAL group id, so a mesh-sharded run
        (parallel/sharded.py, where this peer sees a G/gg-row block)
        draws bit-identical timeouts to the single-chip run.
      timer_inc: scalar i32, 0 or 1 — how far the real-time timers
        (election `elapsed`, leader `hb_elapsed`) advance this step.
        The host's event-driven loop (runtime/node.py) runs extra
        work-triggered steps with timer_inc=0 so message processing can
        outpace the wall-clock tick without distorting election or
        heartbeat timing; interval-paced steps pass 1 (the reference's
        100 ms Tick(), raft.go:207, is exactly the timer_inc=1 cadence).
        Values > 1 apply several intervals of advance at once — the host
        elides steps while nothing can fire (info.timer_margin) and
        settles the accumulated advance on the next real step.
      force_bcast: scalar bool OR [G] bool — leaders broadcast an
        append/heartbeat round THIS step regardless of heartbeat
        countdown.  The host sets it when a linearizable read
        registers (runtime/node.py read_index / read_join): the
        ReadIndex quorum round must not wait out the heartbeat
        interval.  A [G] mask (the batched-ReadIndex promote,
        runtime/node.py _rb_promote) nudges only the groups with
        reads in flight; it broadcasts against the same [G] hb_fire
        vector a scalar does, so semantics per group are identical.

    Returns:
      (new_state, outbox, info).  `outbox[g, dst]` is the dense message set
      to deliver; `info` carries the host-facing signals (commit advance,
      accepted proposals, accepted append ranges) that drive WAL writes,
      payload mirroring, and apply.
    """
    G, P, W, E = cfg.num_groups, cfg.num_peers, cfg.log_window, \
        cfg.max_entries_per_msg
    src_ids = jnp.arange(P, dtype=I32)[None, :]                  # [1, P]
    self_onehot = src_ids == self_id                             # [1, P]

    # Active membership configuration (device data, raftsql_tpu/
    # membership/): every quorum below — commit advance, election
    # tally, prevote tally, vote granting — reads these masks, so N
    # groups can sit in N different configurations inside this one
    # program.  The static all-voters default reproduces the old fixed
    # cfg.quorum math bit for bit.  `voter_src[g, p]` = slot p is a
    # voter of group g under EITHER mask (joint consensus counts both);
    # `self_voter[g]` = this peer may campaign.
    #
    # STATIC fast path (cfg.static_full_voters): the masks are known
    # full constants, so every mask gate folds to identity and every
    # quorum is the fixed-threshold kernel — the pre-membership program.
    # The masked kernels with a full mask are bit-identical (property-
    # tested in tests/test_membership.py), so the two paths may never
    # diverge; runtimes flip to the dynamic path (one recompile) the
    # moment a conf entry exists (config.py dynamic_membership).
    voters, jvoters = state.voters, state.voters_joint
    if cfg.static_full_voters:
        voter_src = True           # folds out of every & below
        self_voter = True

        def _vote_win(votes):
            # election_size == cfg.quorum under default geometry; an
            # explicit election_quorum (config.py flexible quorums)
            # just substitutes the static threshold constant.
            return vote_count(votes) >= cfg.election_size
    else:
        voter_src = voters | jvoters                             # [G, P]
        self_voter = jnp.sum(voter_src & self_onehot,
                             axis=-1) > 0                        # [G]

        def _vote_win(votes):
            return masked_vote_win(votes, voters, jvoters,
                                   cfg.election_quorum)

    # Witness self-identity (config.py witnesses): a STATIC [P] bool
    # constant indexed by the traced self_id — witnesses are a
    # deployment shape, never device state, so the same compiled
    # program serves every peer under vmap (core/cluster.py).  The
    # default (no witnesses) keeps a Python False that folds out of
    # every gate below, leaving the program bit-identical.
    if cfg.witnesses:
        self_witness = jnp.asarray(witness_row(cfg))[self_id]    # scalar
    else:
        self_witness = False

    log_term, log_len = state.log_term, state.log_len
    tbl_pos, tbl_term = state.tbl_pos, state.tbl_term
    commit0 = state.commit
    # Every term-of-position read below goes through the O(K) transition
    # table (state.tbl_pos/tbl_term); the O(W) ring is write-only here
    # (it feeds the windowed/pallas commit rules and test oracles).
    # Positions below the table floor are unreadable and guarded like
    # out-of-ring positions.
    floor0 = tbl_floor(tbl_pos, log_len)                          # [G]

    def term_of0(idx):  # reads against the PRE-append log
        return term_at_tbl(tbl_pos, tbl_term, log_len, idx)

    # ---- Phase 1: term catch-up.  Any message with a newer term makes us a
    # follower of that term (raft §5.1) — EXCEPT prevote traffic carrying a
    # *probed* future term: PREREQ (the probe itself) and granted PRERESP
    # (echoing the probed term back) must not bump anyone, or prevote would
    # inflate terms exactly like the elections it prevents.  A REJECTED
    # PRERESP carries the responder's real current term and does bump.
    v_bump = (inbox.v_type == MSG_REQ) | (inbox.v_type == MSG_RESP) \
        | ((inbox.v_type == MSG_PRERESP) & ~inbox.v_granted)
    a_has = inbox.a_type != MSG_NONE
    msg_term = jnp.maximum(
        jnp.max(jnp.where(v_bump, inbox.v_term, 0), axis=-1),
        jnp.max(jnp.where(a_has, inbox.a_term, 0), axis=-1))      # [G]
    bumped = msg_term > state.term
    term = jnp.maximum(state.term, msg_term)
    role = jnp.where(bumped, FOLLOWER, state.role)
    voted = jnp.where(bumped, NO_VOTE, state.voted_for)
    votes = jnp.where(bumped[:, None], False, state.votes)
    leader_hint = jnp.where(bumped, NO_LEADER, state.leader_hint)

    my_last_term = term_of0(log_len)                              # [G]

    # ---- Phase 1b: TimeoutNow receipt (leadership transfer, raft thesis
    # §3.10).  A caught-up transfer target starts a REAL election at
    # term+1 immediately — no prevote probe, which is exactly how the
    # grant bypasses the Phase-2b in-lease refusal for this one peer
    # (every other peer keeps refusing in-lease probes, so nobody else
    # can race the handoff inside the lease).  Gated on the sender's
    # CURRENT term (a stale grant from a deposed leader is ignored) and
    # on self being a voter (learners/spares never campaign, Phase 8).
    # With no transfer armed anywhere (xfer_target all NO_XFER — the
    # shipping default) no MSG_TIMEONOW ever exists and this phase is a
    # no-op: trajectories stay bit-identical to the pre-transfer kernel.
    tnow_fire = ((inbox.v_type == MSG_TIMEONOW)
                 & (inbox.v_term == term[:, None])).any(-1) \
        & (role != LEADER) & self_voter
    if cfg.witnesses:
        # Witnesses never campaign, so they never accept a transfer
        # grant either (the host refuses witness targets up front —
        # runtime TransferRefused — this is the device-side backstop).
        tnow_fire = tnow_fire & ~self_witness
    term = jnp.where(tnow_fire, term + 1, term)
    role = jnp.where(tnow_fire, CANDIDATE, role)
    voted = jnp.where(tnow_fire, self_id, voted)
    votes = jnp.where(tnow_fire[:, None],
                      jnp.broadcast_to(self_onehot, (G, P)), votes)
    leader_hint = jnp.where(tnow_fire, NO_LEADER, leader_hint)

    # ---- Phase 2: RequestVote requests.  Grant at most one vote per group
    # per tick (voted_for is single-valued); re-granting to the same
    # candidate is idempotent.
    vreq = inbox.v_type == MSG_REQ
    vreq_cur = vreq & (inbox.v_term == term[:, None])
    up2date = (inbox.v_last_term > my_last_term[:, None]) | (
        (inbox.v_last_term == my_last_term[:, None])
        & (inbox.v_last_idx >= log_len[:, None]))
    # voter_src gate: never grant to a candidate WE believe is outside
    # the active configuration — once a removal commits at a majority,
    # the removed peer can no longer assemble a quorum of grants ("no
    # quorum from a removed majority", chaos/invariants.py).
    eligible = vreq_cur & up2date & voter_src & (
        (voted == NO_VOTE)[:, None] | (voted[:, None] == src_ids))
    any_grant = eligible.any(-1)
    grant_to = jnp.argmax(eligible, axis=-1).astype(I32)          # [G]
    grant = eligible & (src_ids == grant_to[:, None])             # [G, P]
    voted = jnp.where(any_grant, grant_to, voted)

    # ---- Phase 2b: PreVote requests.  Grant iff the probe targets a term
    # ahead of ours, the prober's log is up-to-date, and we are NOT inside
    # a live leader's lease (heard from it within one election interval) —
    # the lease test is what starves a partitioned prober while the
    # cluster is healthy.  Prevote grants persist nothing (not voted_for),
    # so any number may be granted per tick, one per source slot.
    preq = inbox.v_type == MSG_PREREQ
    if cfg.prevote:
        in_lease = (leader_hint != NO_LEADER) & \
            (state.elapsed < cfg.election_ticks)
        lease_ok = ~in_lease[:, None]
        if cfg.unsafe_witness_lease and cfg.witnesses:
            # FALSIFICATION ONLY (config.py unsafe_witness_lease): the
            # "witness as always-available tiebreaker" mistake — this
            # witness grants prevotes INSIDE a live lease while its
            # append acks still feed the lease clock (Phase 8b), so an
            # election can complete before the lease expires and the
            # deposed leader serves a stale lease read.  The quorum
            # chaos family must CATCH it.
            lease_ok = lease_ok | self_witness
        pre_grant = preq & (inbox.v_term > term[:, None]) & up2date \
            & voter_src & lease_ok
    else:
        pre_grant = jnp.zeros_like(preq)

    # Vote-slot responses must be stamped with the term their grant/reject
    # was DECIDED at (here, before the Phase-3 prevote promotion can bump
    # our term) — a grant decided at T but stamped T+1 would depose the
    # very candidate it was granted to via the Phase-1 bump rule.
    vterm_resp = term

    # ---- Phase 3: vote tallies.  First the prevote tally (promotes
    # PRECANDIDATE → CANDIDATE, bumping the term only now that a quorum
    # said the election could win), then the real-vote tally — a just-
    # promoted candidate holding its own vote can win leadership in the
    # same tick when P == 1.
    if cfg.prevote:
        got_pre = (inbox.v_type == MSG_PRERESP) & inbox.v_granted \
            & (inbox.v_term == term[:, None] + 1) \
            & (role == PRECANDIDATE)[:, None]
        votes = votes | got_pre
        become_cand = (role == PRECANDIDATE) & _vote_win(votes)
        term = jnp.where(become_cand, term + 1, term)
        role = jnp.where(become_cand, CANDIDATE, role)
        voted = jnp.where(become_cand, self_id, voted)
        votes = jnp.where(become_cand[:, None],
                          jnp.broadcast_to(self_onehot, (G, P)), votes)

    got_vote = (inbox.v_type == MSG_RESP) & (inbox.v_term == term[:, None]) \
        & inbox.v_granted & (role == CANDIDATE)[:, None]
    votes = votes | got_vote
    become_leader = (role == CANDIDATE) & _vote_win(votes)
    role = jnp.where(become_leader, LEADER, role)
    leader_hint = jnp.where(become_leader, self_id, leader_hint)
    next_idx = jnp.where(become_leader[:, None], log_len[:, None] + 1,
                         state.next_idx)
    match = jnp.where(become_leader[:, None], 0, state.match)

    # ---- Phase 4: AppendEntries requests.  At most one current-term leader
    # exists (election safety), so picking one current-term append per group
    # loses nothing.
    areq = inbox.a_type == MSG_REQ
    areq_cur = areq & (inbox.a_term == term[:, None])
    any_app = areq_cur.any(-1)
    asrc = jnp.argmax(areq_cur, axis=-1).astype(I32)              # [G]
    role = jnp.where(
        any_app & ((role == CANDIDATE) | (role == PRECANDIDATE)),
        FOLLOWER, role)
    leader_hint = jnp.where(any_app, asrc, leader_hint)

    def pick(x):  # gather the chosen source's message fields → [G, ...]
        return dense.pick_peer(x, asrc)

    prev = pick(inbox.a_prev_idx)
    prev_t = pick(inbox.a_prev_term)
    a_n = pick(inbox.a_n)
    a_ents = pick(inbox.a_ents)                                   # [G, E]
    a_commit = pick(inbox.a_commit)

    # Log-matching check — but ONLY against positions whose term is
    # still known: below the table floor the term is gone, and a stale
    # append (old leader, or one raced by an InstallSnapshot that
    # cleared the log metadata) must be rejected rather than trusted —
    # accepting it would conflict-truncate a log it never matched.
    # Two ways to verify a batch:
    #   1. directly at prev (prev above the floor, terms match); or
    #   2. at the batch's LAST overlapping position, when that is above
    #      the floor and terms match there — by the Log Matching
    #      property a shared (index, term) implies the whole prefix
    #      (prev included) matches.  This unsticks a live deadlock: a
    #      restarted follower whose own floor sits above the leader's
    #      serving point would otherwise reject every catch-up append
    #      (its reject hints can only walk next_idx DOWN), while the
    #      anchor check lets it accept the overlap it already holds and
    #      ack match=app_end.
    # prev == 0 is only exempt while the table still covers position 1.
    ov_n = jnp.clip(jnp.minimum(prev + a_n, log_len) - prev, 0, E)  # [G]
    ov_term = term_of0(prev + ov_n)
    batch_ov = dense.pick_batch(a_ents, jnp.maximum(ov_n - 1, 0))
    anchor_ok = (ov_n > 0) & (prev + ov_n >= floor0) \
        & (ov_term == batch_ov)
    prev_ok = ((prev == 0) & (floor0 <= 1)) \
        | ((prev <= log_len) & (prev >= floor0)
           & (term_of0(prev) == prev_t)) \
        | ((prev <= log_len) & anchor_ok)
    accept = any_app & prev_ok & (role != LEADER)

    # Conflict detection reuses the endpoint read from above: by Log
    # Matching, a term mismatch anywhere in the overlap implies one at
    # the LAST overlapping position — one [G] table read replaces a
    # [G, E]-wide per-position scan (which profiled as 34% of the TPU
    # tick, see ops/dense.py).
    conflict = accept & (ov_n > 0) & (ov_term != batch_ov)
    # Ring write of the accepted batch, scatter-free (ops/dense.py): entry
    # e lands at slot (prev+e) % W, so slot w holds batch element
    # (w - prev) mod W when that is < n.  One-hot over E replaces the
    # serialized XLA scatter the TPU path cannot afford.  Positions at or
    # below (new log_len) - W are masked out: an anchor-verified batch
    # may sit arbitrarily deep, and its slots would alias LIVE ring
    # entries of newer positions.
    a_n_w = jnp.clip(a_n, 0, E)
    if cfg.keep_ring:
        wpos = jnp.arange(W, dtype=I32)[None, :]                   # [1, W]
        rel4 = (wpos - prev[:, None]) % W                          # [G, W]
        len_after = jnp.where(conflict, prev + a_n,
                              jnp.maximum(log_len, prev + a_n))    # [G]
        pos4 = prev[:, None] + 1 + rel4
        hit4 = accept[:, None] & (rel4 < a_n_w[:, None]) \
            & (pos4 > len_after[:, None] - W)
        vals4 = dense.ring_gather_values(a_ents, rel4, a_n_w)
        log_term = jnp.where(hit4, vals4, log_term)
    app_end = prev + a_n
    follower_len0 = log_len
    log_len = jnp.where(
        accept,
        jnp.where(conflict, app_end, jnp.maximum(log_len, app_end)),
        log_len)

    # Transition-table merge for the accepted batch.  Old transitions
    # survive up to the first rewritten-and-changed position (everything
    # on conflict-free overlap is unchanged by Log Matching); new
    # transitions come from term changes inside the batch's genuinely
    # new span.  Candidates stay position-ascending by construction
    # (kept old <= boundary < added new), so compaction is a reversed
    # prefix-count that right-aligns the newest K — no sort.
    new_from = jnp.where(conflict, prev, follower_len0)           # [G]
    old_keep = (tbl_pos > 0) & (
        ~(accept & conflict)[:, None] | (tbl_pos <= prev[:, None]))
    erange = jnp.arange(E, dtype=I32)[None, :]
    pos_e = prev[:, None] + 1 + erange                            # [G, E]
    prev_term_known = term_of0(prev)                              # [G]
    ents_shift = jnp.concatenate(
        [prev_term_known[:, None], a_ents[:, :-1]], axis=-1)      # [G, E]
    bnd = a_ents != ents_shift
    new_add = accept[:, None] & (erange < a_n_w[:, None]) \
        & (pos_e > new_from[:, None]) & bnd                       # [G, E]
    K = tbl_pos.shape[-1]
    cand_pos = jnp.concatenate(
        [jnp.where(old_keep, tbl_pos, 0), jnp.where(new_add, pos_e, 0)], -1)
    cand_term = jnp.concatenate(
        [jnp.where(old_keep, tbl_term, 0), jnp.where(new_add, a_ents, 0)],
        -1)                                                       # [G, K+E]
    cvalid = cand_pos > 0
    # r[i] = number of valid candidates strictly after i; keep the newest
    # K and right-align them at slot K-1-r.
    r = jnp.cumsum(cvalid[:, ::-1], axis=-1)[:, ::-1] - cvalid
    keep = cvalid & (r < K)
    slot = jnp.where(keep, K - 1 - r, K)                          # K = drop
    krange = jnp.arange(K, dtype=slot.dtype)
    hit_k = slot[:, :, None] == krange                            # [G,K+E,K]
    merged_pos = jnp.sum(jnp.where(hit_k, cand_pos[:, :, None], 0), axis=1)
    merged_term = jnp.sum(jnp.where(hit_k, cand_term[:, :, None], 0), axis=1)
    tbl_pos = jnp.where(accept[:, None], merged_pos, tbl_pos)
    tbl_term = jnp.where(accept[:, None], merged_term, tbl_term)
    # Raft Fig. 2: commit = min(leaderCommit, index of last new entry).  The
    # clamp to app_end (not log_len) matters: positions beyond the accepted
    # batch are unverified and may diverge from the leader.
    commit = jnp.where(accept,
                       jnp.maximum(commit0, jnp.minimum(a_commit, app_end)),
                       commit0)

    # ---- Phase 5: AppendEntries responses → leader match/next bookkeeping.
    rs = (inbox.a_type == MSG_RESP) & (inbox.a_term == term[:, None]) \
        & (role == LEADER)[:, None]
    rs_ok = rs & inbox.a_success
    rs_fail = rs & ~inbox.a_success
    match = jnp.where(rs_ok, jnp.maximum(match, inbox.a_match), match)
    next_idx = jnp.where(rs_ok, jnp.maximum(next_idx, inbox.a_match + 1),
                         next_idx)
    # On reject, back off to the follower's conflict hint (its log
    # length), the fast-backoff analog of etcd's rejection hints.  A
    # floor-reject resync request (Phase 4's floor_rej: the follower can
    # only verify appends near its tip) arrives EXPLICITLY marked with
    # FLOOR_HINT_BIAS on the hint; strip the bias and JUMP next_idx up
    # to hint + 1.  Ordinary hints only ever walk next_idx down — with
    # the explicit flag, a late in-flight ordinary reject (whose hint a
    # previous reject already walked below) can no longer be mistaken
    # for a resync and re-probe ground the leader already ruled out.  A
    # stale/bogus biased hint self-corrects: the probe append at the
    # jumped prev is itself verified (or floor-rejected with an honest
    # hint) by the follower.
    is_floor_hint = inbox.a_match >= FLOOR_HINT_BIAS
    hint = inbox.a_match - jnp.where(is_floor_hint, FLOOR_HINT_BIAS, 0)
    walked = jnp.clip(jnp.minimum(next_idx - 1, hint + 1), 1, None)
    next_idx = jnp.where(
        rs_fail,
        jnp.where(is_floor_hint, hint + 1, walked),
        next_idx)
    next_idx = jnp.maximum(next_idx, match + 1)

    # ---- Phase 6: proposals (+ the new-leader no-op entry).
    is_leader = role == LEADER
    # Leadership transfer in flight (thesis §3.10 step 1): the group
    # stops accepting NEW proposals so the target's match can converge
    # on a fixed log tip.  Queued proposals stay queued on the host and
    # drain to the new leader (or to us again, after a host abort clears
    # the latch) — never dropped.  All-NO_XFER (the default) makes this
    # mask all-False and n_acc bit-identical to the untransferred kernel.
    transferring = is_leader & (state.xfer_target != NO_XFER)
    n_acc = jnp.where(transferring, 0, prop_n)
    # Flow control: never let uncommitted depth overrun the term ring.  The
    # no-op consumes space too — a flapping leadership under a stalled
    # commit must not grow the log unboundedly.
    space = jnp.maximum(W - 2 * E - (log_len - commit), 0)
    noop_n = (become_leader & (space >= 1)).astype(I32)
    n_acc = jnp.where(is_leader,
                      jnp.minimum(jnp.minimum(n_acc, E), space - noop_n), 0)
    total_app = noop_n + n_acc
    prop_base = log_len + noop_n
    # Appended entries all carry the leader's current term, so this ring
    # write is a pure mask fill (no scatter, no value gather): slot w is
    # written iff (w - log_len) mod W < total_app, i.e. it holds one of
    # positions log_len+1 .. log_len+total_app.
    if cfg.keep_ring:
        rel6 = (wpos - log_len[:, None]) % W                       # [G, W]
        log_term = jnp.where(rel6 < total_app[:, None], term[:, None],
                             log_term)
    # Table push: appends are all at the leader's current term, so at most
    # one new transition — at the first appended position, iff the log's
    # newest term differs.  Right-aligned layout makes this a static
    # shift-left + write of slot K-1.
    push = (total_app > 0) & (tbl_term[:, K - 1] != term)
    shifted_pos = jnp.concatenate(
        [tbl_pos[:, 1:], (log_len + 1)[:, None]], axis=-1)
    shifted_term = jnp.concatenate(
        [tbl_term[:, 1:], term[:, None]], axis=-1)
    tbl_pos = jnp.where(push[:, None], shifted_pos, tbl_pos)
    tbl_term = jnp.where(push[:, None], shifted_term, tbl_term)
    log_len = log_len + total_app

    def term_of1(idx):  # reads against the POST-append log
        return term_at_tbl(tbl_pos, tbl_term, log_len, idx)

    floor1 = tbl_floor(tbl_pos, log_len)                          # [G]
    match = jnp.where(is_leader[:, None] & self_onehot, log_len[:, None],
                      match)

    # ---- Phase 7: leader commit advance — the quorum reduction kernel
    # (selected by cfg.commit_rule; all implement raft Fig. 2's leader
    # rule, see ops/commit_scan.py and ops/pallas_quorum.py).
    # All four kernels take the WRITE quorum (config.py flexible
    # quorums): write_size == cfg.quorum under default geometry, so the
    # static constants (and the masked kernels' None size) compile the
    # digest-pinned program unchanged.
    if cfg.commit_rule == "windowed":
        if cfg.static_full_voters:
            from raftsql_tpu.ops.commit_scan import windowed_commit_index
            commit = windowed_commit_index(
                match, log_term, log_len, commit, term, is_leader,
                quorum=cfg.write_size, window=W)
        else:
            from raftsql_tpu.ops.commit_scan import \
                masked_windowed_commit_index
            commit = masked_windowed_commit_index(
                match, log_term, log_len, commit, term, is_leader,
                voters=voters, voters_joint=jvoters, window=W,
                size=cfg.write_quorum)
    elif cfg.commit_rule == "pallas":
        if cfg.static_full_voters:
            from raftsql_tpu.ops.pallas_quorum import \
                pallas_quorum_commit_index
            commit = pallas_quorum_commit_index(
                match, log_term, log_len, commit, term, is_leader,
                quorum=cfg.write_size, window=W)
        else:
            from raftsql_tpu.ops.pallas_quorum import \
                pallas_masked_quorum_commit_index
            commit = pallas_masked_quorum_commit_index(
                match, log_term, log_len, commit, term, is_leader,
                voters=voters, voters_joint=jvoters, window=W,
                size=cfg.write_quorum)
    elif cfg.static_full_voters:
        commit = quorum_commit_index(
            match, log_term, log_len, commit, term, is_leader,
            quorum=cfg.write_size, window=W, term_of=term_of1)
    else:
        commit = masked_quorum_commit_index(
            match, log_term, log_len, commit, term, is_leader,
            voters=voters, voters_joint=jvoters, window=W,
            term_of=term_of1, size=cfg.write_quorum)

    # ---- Phase 8: timers and election start.  tnow_fire counts as a
    # reset: the transfer target just started a REAL election (Phase 1b)
    # and must not immediately re-fire as a PRECANDIDATE on a stale
    # elapsed counter, which would demote the in-flight candidacy.
    reset = any_grant | any_app | tnow_fire
    elapsed = jnp.where(is_leader | reset, 0, state.elapsed + timer_inc)
    # Learners/spares (self outside both masks) never campaign: their
    # timers tick but cannot fire — they follow whoever the voters
    # elect and wait for a conf entry to promote them.
    fire = (role != LEADER) & (elapsed >= state.timeout) & self_voter
    if cfg.witnesses:
        # Witnesses vote and persist but never campaign or lead: their
        # election timers tick (they still grant, and their timer state
        # feeds the lease exclusion window) but cannot fire.
        fire = fire & ~self_witness
    term_resp = term          # term used in responses composed above
    if cfg.prevote:
        # Timeout starts a PROBE, not an election: role flips to
        # PRECANDIDATE at the unchanged term, self-prevote is tallied,
        # nothing is persisted.  The term bumps only in Phase 3 when a
        # quorum grants the probe — so a partitioned peer can fire
        # forever without inflating its term.
        role = jnp.where(fire, PRECANDIDATE, role)
        votes = jnp.where(fire[:, None],
                         jnp.broadcast_to(self_onehot, (G, P)), votes)
    else:
        term = jnp.where(fire, term + 1, term)
        role = jnp.where(fire, CANDIDATE, role)
        voted = jnp.where(fire, self_id, voted)
        votes = jnp.where(fire[:, None],
                          jnp.broadcast_to(self_onehot, (G, P)), votes)
    leader_hint = jnp.where(fire, NO_LEADER, leader_hint)
    elapsed = jnp.where(fire, 0, elapsed)
    # Per-group timeout re-draw via an integer hash (ops/dense.py): the
    # threefry chain this replaces (~40 HLOs) dominated tick wall time on
    # the TPU path; the hash keeps the same contract (deterministic in
    # seed/peer/tick/global gid, uniform over the span).
    gids = jnp.asarray(group_offset, I32) + jnp.arange(G, dtype=I32)
    new_timeout = dense.election_jitter(
        dense.key_data_of(state.rng), state.tick, gids,
        cfg.election_ticks, 2 * cfg.election_ticks)
    timeout = jnp.where(fire, new_timeout, state.timeout)

    hb = jnp.where(is_leader, state.hb_elapsed + timer_inc, 0)
    # commit > commit0: broadcast the new commit index NOW rather than on
    # the next heartbeat — a follower-proposed entry's ack waits on its
    # proposer LEARNING the commit, and heartbeat-paced propagation put a
    # ~heartbeat/2 floor under propose→ack latency under light load.
    hb_fire = is_leader & ((hb >= cfg.heartbeat_ticks) | become_leader
                           | (total_app > 0) | force_bcast
                           | (commit > commit0))
    hb = jnp.where(hb_fire, 0, hb)

    # ---- Phase 8b: leader leases (raft §6.4.1, config.lease_ticks).
    # Evidence = the newest CURRENT-term append response from each peer
    # (success or reject — either way the responder processed an append
    # at our term, which reset its election timer, Phase 8's `reset`):
    # stamp the device step it was processed at.  A response observed
    # at step T answers a round the responder processed at T-1, so the
    # quorum-th largest stamp minus 1 is when a quorum's election
    # timers were last known reset — any NEW quorum must intersect that
    # set (quorum intersection), and the prevote lease check (Phase 2b)
    # keeps every member of it from granting a probe for election_ticks
    # of its own clock.  The lease never feeds back into consensus:
    # resp_tick/lease are write-only outputs, so a disabled lease
    # (lease_ticks == 0, the default) leaves every trajectory
    # bit-identical with the kernel compiled in.
    tick_now = state.tick
    lease_role = role == LEADER          # post-Phase-8 (leaders never fire)
    resp_tick = jnp.where(bumped[:, None], 0, state.resp_tick)
    resp_tick = jnp.where(rs, tick_now, resp_tick)
    resp_tick = jnp.where(become_leader[:, None], 0, resp_tick)
    # The leader's own slot counts as confirmed NOW; non-leaders carry
    # no evidence at all (a deposed-and-reelected leader restarts its
    # lease from scratch).
    resp_tick = jnp.where(
        lease_role[:, None],
        jnp.where(self_onehot, tick_now, resp_tick), 0)
    if cfg.lease_ticks > 0:
        if cfg.static_full_voters:
            # The lease clock is WRITE-quorum evidence (append acks).
            q_tick = quorum_match_index(resp_tick, cfg.write_size)
        else:
            # Joint consensus: the lease needs a quorum of BOTH masks
            # (a read served on the old majority alone could miss a
            # leader elected by the new one, and vice versa).
            q_tick = jnp.minimum(
                masked_quorum_match_index(resp_tick, voters,
                                          cfg.write_quorum),
                masked_quorum_match_index(resp_tick, jvoters,
                                          cfg.write_quorum))
        # §6.4 precondition, folded in on device: the lease read's
        # target is the leader's commit index, which is only current
        # once an entry of its own term has committed.
        cur_ok = (commit >= 1) & (term_of1(commit) == term)
        lease_until = jnp.where(
            lease_role & cur_ok & (q_tick > 0),
            q_tick - 1 + jnp.int32(cfg.lease_ticks), 0)
    else:
        lease_until = jnp.zeros((G,), I32)

    # ---- Phase 9: compose the outbox.  Write order = priority order:
    # responses first, then candidate vote-request broadcast, then leader
    # append broadcast.  A later write overriding a response is safe: every
    # message carries the sender term, and raft re-sends on the next tick.
    my_last_term2 = term_of1(log_len)

    is_cand = role == CANDIDATE
    cand_bcast = is_cand[:, None] & ~self_onehot
    # Prevote probes broadcast at term+1 (the term an election WOULD use);
    # prevote responses echo the probed term on grant (so the prober's
    # tally can match it against term+1) and our real term on reject (so
    # a stale prober catches up via the Phase-1 bump rule).
    # Responses OUTRANK the probe broadcast in a contended slot: when two
    # precandidates probe each other, each must answer the other's probe
    # (the probe to that peer re-sends next tick — and a granted answer
    # promotes both, breaking the tie through a real election).  If the
    # probe instead clobbered the response, three simultaneous
    # precandidates would starve forever: a probe can only be answered by
    # a non-probing peer, and none remains.
    pre_bcast = (role == PRECANDIDATE)[:, None] & ~self_onehot
    o_v_type = jnp.where(cand_bcast, MSG_REQ,
                         jnp.where(vreq, MSG_RESP,
                                   jnp.where(preq, MSG_PRERESP,
                                             jnp.where(pre_bcast, MSG_PREREQ,
                                                       MSG_NONE))))
    resp_term = jnp.where(pre_grant, inbox.v_term,
                          jnp.broadcast_to(vterm_resp[:, None], (G, P)))
    o_v_term = jnp.where(cand_bcast, term[:, None],
                         jnp.where(vreq | preq, resp_term,
                                   jnp.where(pre_bcast, term[:, None] + 1,
                                             resp_term)))
    o_v_last_idx = jnp.broadcast_to(log_len[:, None], (G, P))
    o_v_last_term = jnp.broadcast_to(my_last_term2[:, None], (G, P))
    o_v_granted = (grant | pre_grant) & ~cand_bcast

    # Leadership transfer, leader side (thesis §3.10 steps 2-3): while a
    # transfer is armed, fire MSG_TIMEONOW at the target once its MATCH
    # covers our whole log — re-sent every tick while the latch holds,
    # so a lost grant costs a tick, not the transfer.  The target must
    # be a real peer and a voter under the ACTIVE configuration (either
    # mask during a joint change — the same eligibility the vote-grant
    # gate enforces, so an electable target is never refused and a
    # learner/spare never granted).  The write tops the vote-slot
    # priority chain for that one dst; a clobbered response re-sends
    # next tick (raft tolerates loss).  All-NO_XFER keeps every gate
    # here false.
    xfer = state.xfer_target                                      # [G]
    tgt_clip = jnp.clip(xfer, 0, P - 1)
    tgt_is_voter = dense.pick_peer(
        (voters | jvoters).astype(I32), tgt_clip) > 0             # [G]
    tgt_caught = dense.pick_peer(match, tgt_clip) >= log_len      # [G]
    send_tnow = transferring & (xfer >= 0) & (xfer < P) \
        & (xfer != self_id) & tgt_is_voter
    if not cfg.unsafe_transfer:
        send_tnow = send_tnow & tgt_caught
    tnow_dst = send_tnow[:, None] & (src_ids == tgt_clip[:, None])  # [G, P]
    o_v_type = jnp.where(tnow_dst, MSG_TIMEONOW, o_v_type)
    o_v_term = jnp.where(tnow_dst, term[:, None], o_v_term)
    o_v_granted = o_v_granted & ~tnow_dst
    if cfg.unsafe_transfer:
        # FALSIFICATION ONLY (config.py unsafe_transfer): fire without
        # the catch-up gate and abdicate the instant the grant goes out
        # — the §3.10 mistake the transfer chaos family must catch.
        role = jnp.where(send_tnow, FOLLOWER, role)
        leader_hint = jnp.where(send_tnow, NO_LEADER, leader_hint)

    # Append responses (to every append request seen, incl. stale-term ones
    # so old leaders step down).
    chosen_mask = areq_cur & (src_ids == asrc[:, None]) & any_app[:, None]
    succ = chosen_mask & accept[:, None]
    # Conflict hint on reject: our pre-append log length — EXCEPT when
    # the reject was a FLOOR reject (prev below what our transition
    # table can verify): then the useful serving point is our full log
    # length, whose prev we can always verify (floor <= newest
    # transition <= log_len), and a hint at-or-beyond the leader's send
    # point tells it to resync UP (Phase 5) instead of walking down —
    # without this, a leader serving below a restarted follower's floor
    # walks next_idx to 1 and the pair livelocks on rejects.
    floor_rej = chosen_mask & ~accept[:, None] & (prev < floor0)[:, None]
    rej_hint = jnp.clip(jnp.minimum(prev - 1, follower_len0), 0, None)
    # Floor rejects carry the follower's full log length PLUS the
    # explicit FLOOR_HINT_BIAS marker (see Phase 5 / config.py): the
    # leader must resync UP to this tip, not walk down.
    resp_match = jnp.where(
        succ, app_end[:, None],
        jnp.where(floor_rej, follower_len0[:, None] + FLOOR_HINT_BIAS,
                  jnp.where(chosen_mask, rej_hint[:, None], 0)))

    # Leader append broadcast: to every peer with pending entries, plus
    # everyone on heartbeat.
    send_app = is_leader[:, None] & ~self_onehot & (
        hb_fire[:, None] | (next_idx <= log_len[:, None]))
    prev_s = jnp.clip(next_idx - 1, 0, log_len[:, None])          # [G, P]
    n_s = jnp.clip(log_len[:, None] - prev_s, 0, E)
    # Term-window guard: every position this message reads (prev_s and
    # the batch entries) must still have a KNOWN term — inside the W
    # ring AND at or above the transition-table floor.  A follower
    # lagging past either limit instead gets an EMPTY heartbeat at
    # prev=0, which resets its election timer either way: a receiver
    # whose own table floor is <= 1 accepts it (matches, carries no
    # entries, commit clamp min(leaderCommit, app_end=0) moves
    # nothing), while one whose floor rose past 1 (post-install, or >K
    # transitions) REJECTS it — harmless churn, since the timer reset
    # rides any_app, not accept.  Either way the laggard cannot depose
    # the live leader by starting elections, cannot win one meanwhile
    # (log up-to-dateness check), and actual catch-up is host-mediated
    # (runtime/node.py) — so safety holds while it lags.
    win_floor = log_len[:, None] - W                              # [G, 1]
    min_acc = jnp.where(prev_s > 0, prev_s,
                        jnp.where(n_s > 0, 1, 0))
    in_window = (min_acc == 0) | ((min_acc > win_floor)
                                  & (min_acc >= floor1[:, None]))
    prev_s = jnp.where(in_window, prev_s, 0)
    n_s = jnp.where(in_window, n_s, 0)
    prev_t_s = term_of1(prev_s)                                   # [G, P]
    ent_pos_s = prev_s[:, :, None] + 1 \
        + jnp.arange(E, dtype=I32)[None, None, :]                 # [G, P, E]
    ents_s = term_of1(ent_pos_s.reshape(G, P * E)).reshape(G, P, E)

    # Pipelined replication (etcd's optimistic sendAppend): advance
    # next_idx past the entries just sent instead of idling an ack round
    # trip — successive ticks then stream DISJOINT batches, so per-group
    # throughput is E entries/tick, not E per RTT, and the propose→commit
    # queue never builds to the flow-control ceiling.  A lost message
    # surfaces as a reject whose conflict hint walks next_idx back
    # (Phase 5), exactly as for any stale next_idx.
    #
    # The advance is capped at max_inflight_msgs batches beyond the
    # follower's acked match (the reference's MaxInflightMsgs window,
    # raft.go:158).  Without the cap, a follower ticking slower than its
    # leader under the newest-wins inbox slot would see only every other
    # (disjoint) batch and reject forever — capped, the leader stalls at
    # the window edge and re-sends the SAME batch each tick until an ack
    # drains it, which a slow follower always eventually processes.
    # maximum(): the cap may sit below a next_idx already learned from a
    # reject hint — stall (never regress) rather than re-send entries the
    # follower already acknowledged holding.
    inflight_cap = match + 1 + cfg.max_inflight_msgs * E
    next_idx = jnp.where(send_app & (n_s > 0),
                         jnp.maximum(next_idx,
                                     jnp.minimum(prev_s + n_s + 1,
                                                 inflight_cap)),
                         next_idx)

    o_a_type = jnp.where(send_app, MSG_REQ,
                         jnp.where(areq, MSG_RESP, MSG_NONE))
    o_a_term = jnp.where(send_app, term[:, None],
                         jnp.broadcast_to(term_resp[:, None], (G, P)))
    o_a_prev_idx = jnp.where(send_app, prev_s, 0)
    o_a_prev_term = jnp.where(send_app, prev_t_s, 0)
    o_a_n = jnp.where(send_app, n_s, 0)
    o_a_ents = jnp.where(send_app[:, :, None], ents_s, 0)
    o_a_commit = jnp.where(send_app, commit[:, None], 0)
    o_a_success = succ & ~send_app
    o_a_match = jnp.where(send_app, 0, resp_match)

    # The two type-code planes are built from Python MSG_* literals, so
    # their jnp.where chains come out weakly-typed — and a jit step
    # traced on a strong empty inbox then RETRACES when its own output
    # is fed back on the next tick (the jit-stability tripwire catches
    # this as a second compile).  Pin them strong to the inbox schema.
    o_v_type = o_v_type.astype(I32)
    o_a_type = o_a_type.astype(I32)
    outbox = Outbox(
        v_type=o_v_type, v_term=o_v_term, v_last_idx=o_v_last_idx,
        v_last_term=o_v_last_term, v_granted=o_v_granted,
        a_type=o_a_type, a_term=o_a_term, a_prev_idx=o_a_prev_idx,
        a_prev_term=o_a_prev_term, a_n=o_a_n, a_ents=o_a_ents,
        a_commit=o_a_commit, a_success=o_a_success, a_match=o_a_match)

    # Transfer latch carry: held only while this row still LEADS the
    # group.  Deposition — by the target's term+1 election (completion),
    # by any other election, or by the unsafe-variant abdication — clears
    # it on device, which is also the host's completion signal (the
    # "xfer" info column below drops to NO_XFER).  A latch armed on a
    # non-leader row (host race with an election) clears the same way.
    xfer = jnp.where(role == LEADER, xfer, NO_XFER)

    new_state = PeerState(
        term=term, voted_for=voted, role=role, leader_hint=leader_hint,
        commit=commit, log_len=log_len, log_term=log_term,
        tbl_pos=tbl_pos, tbl_term=tbl_term,
        elapsed=elapsed, timeout=timeout, hb_elapsed=hb,
        votes=votes, match=match, next_idx=next_idx,
        voters=voters, voters_joint=jvoters,
        resp_tick=resp_tick, xfer_target=xfer,
        rng=state.rng, tick=state.tick + 1)

    # Ticks until any timer could fire with no further input: non-leader
    # election countdown vs leader heartbeat countdown, min over groups,
    # clamped >= 1 (the step that fires a timer resets it, so the true
    # margin after a step is always positive).
    is_leader2 = role == LEADER
    big = jnp.int32(1 << 30)
    elec_rem = jnp.where(is_leader2, big, timeout - elapsed)
    hb_rem = jnp.where(is_leader2, cfg.heartbeat_ticks - hb, big)
    timer_margin = jnp.maximum(
        jnp.minimum(jnp.min(elec_rem), jnp.min(hb_rem)), 1)

    info = StepInfo(
        commit=commit, role=role, term=term, voted_for=voted,
        leader_hint=leader_hint,
        prop_base=prop_base, prop_accepted=n_acc, noop=noop_n > 0,
        app_from=jnp.where(accept, asrc, -1),
        app_start=jnp.where(accept, prev + 1, 0),
        app_n=jnp.where(accept, a_n, 0),
        app_conflict=conflict,
        new_log_len=log_len,
        lease=lease_until,
        xfer=xfer,
        next_idx=next_idx,
        floor=floor1,
        timer_margin=timer_margin)

    return new_state, outbox, info


@functools.partial(jax.jit, static_argnums=0, donate_argnums=(1,))
def peer_step_jit(cfg: RaftConfig, state: PeerState, inbox: Inbox,
                  prop_n: jax.Array, self_id: jax.Array,
                  timer_inc: jax.Array | int = 1,
                  force_bcast: jax.Array | bool = False):
    return peer_step(cfg, state, inbox, prop_n, self_id,
                     timer_inc=timer_inc, force_bcast=force_bcast)


# ---------------------------------------------------------------------------
# Packed host boundary.
#
# The runtime's tick (runtime/node.py) crosses host<->device once per step;
# shipping the Inbox as 14 arrays and reading back Outbox+StepInfo as ~30
# cost ~8x the step kernel itself in per-array dispatch overhead at small G
# (measured: 5.7 ms vs 0.7 ms per step, 3 contended processes, CPU
# backend).  The packed forms move ONE array each way; the slices/stack
# below happen inside the compiled program where XLA fuses them to nothing.

# Column order of the packed [G, P, IB_NCOLS + E] message block (shared by
# inbox and outbox; a_ents occupies the trailing E columns).
MSG_FIELDS = ("v_type", "v_term", "v_last_idx", "v_last_term", "v_granted",
              "a_type", "a_term", "a_prev_idx", "a_prev_term", "a_n",
              "a_commit", "a_success", "a_match")
IB_NCOLS = len(MSG_FIELDS)
# Column order of the packed [G, INFO_NCOLS] StepInfo block (next_idx and
# timer_margin ride alongside, unpacked).
INFO_FIELDS = ("commit", "role", "term", "voted_for", "leader_hint",
               "prop_base", "prop_accepted", "noop", "app_from",
               "app_start", "app_n", "app_conflict", "new_log_len",
               "floor", "lease", "xfer")
INFO_NCOLS = len(INFO_FIELDS)


def unpack_inbox(packed: jax.Array) -> Inbox:
    f = {n: packed[:, :, i] for i, n in enumerate(MSG_FIELDS)}
    f["v_granted"] = f["v_granted"].astype(bool)
    f["a_success"] = f["a_success"].astype(bool)
    return Inbox(a_ents=packed[:, :, IB_NCOLS:], **f)


def pack_outbox(ob: Outbox) -> jax.Array:
    head = jnp.stack([getattr(ob, n).astype(I32) for n in MSG_FIELDS],
                     axis=-1)
    return jnp.concatenate([head, ob.a_ents.astype(I32)], axis=-1)


def pack_info(info: StepInfo) -> jax.Array:
    return jnp.stack([getattr(info, n).astype(I32) for n in INFO_FIELDS],
                     axis=-1)


@functools.partial(jax.jit, static_argnums=0, donate_argnums=(1,))
def peer_step_packed(cfg: RaftConfig, state: PeerState, packed: jax.Array,
                     prop_n: jax.Array, self_id: jax.Array,
                     timer_inc: jax.Array | int = 1,
                     force_bcast: jax.Array | bool = False):
    """peer_step with single-array host I/O: `packed` is
    [G, P, IB_NCOLS+E] i32; returns (state, packed_outbox [G, P,
    IB_NCOLS+E], packed_info [G, INFO_NCOLS], next_idx [G, P],
    timer_margin [])."""
    st, ob, info = peer_step(cfg, state, unpack_inbox(packed), prop_n,
                             self_id, timer_inc=timer_inc,
                             force_bcast=force_bcast)
    return (st, pack_outbox(ob), pack_info(info), info.next_idx,
            info.timer_margin)
