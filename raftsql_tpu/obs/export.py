"""Chrome trace-event export — the JSON object format Perfetto and
chrome://tracing load directly.

One document, three track families:

  * pid 1 "spans": per-group tracks of complete ("X") slices, one per
    adjacent recorded phase pair of every span
    (propose→append→replicate→commit→apply→ack), on the host monotonic
    axis (us since the tracer epoch);
  * pid 2 "host io": the tracer's timeline-event ring (WAL fsyncs, TCP
    frames, ...) as duration slices or instants;
  * pid 3 "device": counter ("C") tracks built from the device event
    ring — commit / inbox depth / vote tally per (peer, group) — on a
    SYNTHETIC tick axis (1 tick = `tick_us` microseconds), since device
    ticks carry no wall clock.  Separate pid, so the axes never mix;
  * pid 4 "tick phases": the tick-phase profiler's per-phase duration
    tracks (obs/prof.py — pop / dispatch / wal_write / fsync / publish
    / ring_drain, one thread per (phase, worker id));
  * real-pid process tracks: per-process trace SEGMENTS merged in from
    the serving plane's worker processes (TraceSegmentWriter /
    collect_segments below) — a `--workers N` deployment's /trace is
    ONE multi-process Perfetto timeline, workers named and keyed by
    their real OS pid.

Cross-process timestamps work because Linux CLOCK_MONOTONIC is one
boot-relative clock shared by every process on the host: segments
store RAW monotonic stamps and chrome_trace rebases everything to one
`base_monotonic` epoch (the engine tracer's, falling back to the
profiler's).

`validate_chrome_trace` is the schema check the tests (and `make
trace`) run over every emitted document, so "Perfetto accepts it" is an
asserted property, not a hope.
"""
from __future__ import annotations

import glob
import json
import os
import threading
import time
from collections import deque
from typing import List, Optional

from raftsql_tpu.obs.spans import PHASES

_ALLOWED_PH = {"X", "B", "E", "i", "I", "C", "M"}


def _meta(pid: int, name: str, tid: Optional[int] = None,
          tname: Optional[str] = None) -> List[dict]:
    out = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": name}}]
    if tid is not None:
        out.append({"name": "thread_name", "ph": "M", "pid": pid,
                    "tid": tid, "args": {"name": tname or str(tid)}})
    return out


def chrome_trace(span_snapshot: Optional[dict] = None,
                 device_rows: Optional[List[dict]] = None,
                 tick_us: float = 1000.0, max_groups: int = 8,
                 phase_events: Optional[List[dict]] = None,
                 process_segments: Optional[List[dict]] = None,
                 base_monotonic: Optional[float] = None) -> dict:
    """Build the trace document from `SpanTracer.snapshot()` and/or
    `DeviceEventRing.rows()`, plus the tick-phase profiler's
    `events()` (`phase_events`) and per-process worker segments
    (`process_segments`, see collect_segments).  Any input may be
    None/empty — the document is always valid (an empty trace loads
    fine).  `base_monotonic` is the raw-monotonic epoch phase/segment
    stamps are rebased to (pass the span tracer's `t0` so every track
    family shares one time axis)."""
    events: List[dict] = []
    events += _meta(1, "raftsql spans")
    seen_groups = set()

    for sp in (span_snapshot or {}).get("spans", ()):
        g = sp["group"]
        if g not in seen_groups and len(seen_groups) < max_groups:
            seen_groups.add(g)
            events += _meta(1, "raftsql spans", tid=g,
                            tname=f"group {g}")[1:]
        ph = sp["phases"]
        stamps = [(name, ph[name]) for name in PHASES if name in ph]
        for (a, ta), (b, tb) in zip(stamps, stamps[1:]):
            events.append({
                "name": f"{a}→{b}", "cat": "span", "ph": "X",
                "ts": ta, "dur": max(tb - ta, 0.0), "pid": 1, "tid": g,
                "args": {"index": sp["index"], "key": sp["key"]}})

    host_events = (span_snapshot or {}).get("events", ())
    if host_events:
        events += _meta(2, "raftsql host io", tid=0, tname="io")
        for ev in host_events:
            rec = {"name": ev["name"], "cat": "io", "ts": ev["ts"],
                   "pid": 2, "tid": 0, "args": ev.get("args", {})}
            if ev.get("dur", 0) > 0:
                rec.update(ph="X", dur=ev["dur"])
            else:
                rec.update(ph="i", s="t")
            events.append(rec)

    if device_rows:
        events += _meta(3, "raftsql device (tick axis)")
        P = len(device_rows[0]["commit"])
        G = min(len(device_rows[0]["commit"][0]), max_groups)
        for row in device_rows:
            ts = row["tick"] * tick_us
            for p in range(P):
                for g in range(G):
                    for field in ("commit", "inbox_depth", "votes"):
                        events.append({
                            "name": f"p{p}/g{g} {field}", "ph": "C",
                            "ts": ts, "pid": 3, "tid": 0,
                            "args": {"value": row[field][p][g]}})

    base = base_monotonic or 0.0

    def _rel_us(raw_s: float) -> float:
        return round(max((raw_s - base) * 1e6, 0.0), 1)

    if phase_events:
        events += _meta(4, "raftsql tick phases")
        tids: dict = {}
        for ev in phase_events:
            key = (ev["phase"], ev.get("tid", 0))
            tid = tids.get(key)
            if tid is None:
                tid = tids[key] = len(tids)
                tname = ev["phase"] if not ev.get("tid") \
                    else f"{ev['phase']} w{ev['tid']}"
                events += _meta(4, "raftsql tick phases", tid=tid,
                                tname=tname)[1:]
            events.append({
                "name": ev["phase"], "cat": "phase", "ph": "X",
                "ts": _rel_us(ev["t0"]),
                "dur": round(max(ev["dur"], 0.0) * 1e6, 1),
                "pid": 4, "tid": tid, "args": {"tick": ev["tick"]}})

    for seg in process_segments or ():
        pid = int(seg.get("pid", 0))
        if pid <= 4:        # never collide with the synthetic tracks
            continue
        events += _meta(pid, seg.get("name", f"pid {pid}"), tid=0,
                        tname="requests")
        for ev in seg.get("events", ()):
            rec = {"name": ev["name"], "cat": "proc",
                   "ts": _rel_us(ev["ts"]), "pid": pid,
                   "tid": int(ev.get("tid", 0)),
                   "args": ev.get("args", {})}
            dur = ev.get("dur", 0.0)
            if dur and dur > 0:
                rec.update(ph="X", dur=round(dur * 1e6, 1))
            else:
                rec.update(ph="i", s="t")
            events.append(rec)

    return {"traceEvents": events, "displayTimeUnit": "ms"}


# ---------------------------------------------------------------------------
# Cross-process trace segments (the --workers serving plane).


class TraceSegmentWriter:
    """Per-process trace segment: a bounded event ring a worker process
    stamps (pid/worker-id tagged) and flushes ATOMICALLY (tmp + rename)
    into the engine's ring directory, where the engine's /trace picks
    it up (collect_segments) and merges it into the single Perfetto
    timeline.  Timestamps are RAW monotonic seconds — one clock per
    host, so the engine can rebase them onto its own trace epoch.

    Bounded and crash-friendly: the ring caps memory, the atomic
    rename means a reader never sees a torn file, and the last flushed
    segment of a SIGKILLed worker stays on disk — its final moments
    remain on the merged timeline."""

    def __init__(self, dirname: str, name: str, tag: Optional[str] = None,
                 cap: int = 4096, flush_s: float = 0.5):
        os.makedirs(dirname, exist_ok=True)
        self.name = name
        self.pid = os.getpid()
        self.path = os.path.join(dirname,
                                 f"trace-seg-{tag or self.pid}.json")
        self.flush_s = flush_s
        self._events: deque = deque(maxlen=cap)
        self._mu = threading.Lock()
        self._dirty = False
        self._last_flush = 0.0

    def note(self, name: str, t_start: float, dur_s: float,
             tid: int = 0, **args) -> None:
        with self._mu:
            self._events.append({"name": name, "ts": t_start,
                                 "dur": dur_s, "tid": tid,
                                 "args": args})
            self._dirty = True

    def maybe_flush(self) -> None:
        """Flush when dirty and at least `flush_s` elapsed — cheap to
        call after every completion batch."""
        if self._dirty and time.monotonic() - self._last_flush \
                >= self.flush_s:
            self.flush()

    def flush(self) -> None:
        with self._mu:
            doc = {"pid": self.pid, "name": self.name,
                   "events": list(self._events)}
            self._dirty = False
        self._last_flush = time.monotonic()
        tmp = self.path + f".tmp{self.pid}"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(doc, f)
            os.replace(tmp, self.path)
        except OSError:       # diagnostics only — never fail the worker
            try:
                os.unlink(tmp)
            except OSError:
                pass


def collect_segments(dirname: str) -> List[dict]:
    """Every flushed per-process trace segment under `dirname`
    (unreadable/corrupt files skipped — a scrape must always render)."""
    out: List[dict] = []
    for path in sorted(glob.glob(os.path.join(dirname,
                                              "trace-seg-*.json"))):
        try:
            with open(path, "r", encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(doc, dict) and isinstance(doc.get("events"), list):
            out.append(doc)
    return out


def validate_chrome_trace(doc: dict) -> None:
    """Raise ValueError unless `doc` is a well-formed Chrome trace-event
    JSON object: serializable, traceEvents a list, every event carrying
    a name, a known phase, a pid, and (for non-metadata phases) a
    non-negative numeric ts; complete events need a non-negative dur,
    counters a numeric value."""
    try:
        json.dumps(doc)
    except (TypeError, ValueError) as e:
        raise ValueError(f"trace not JSON-serializable: {e}") from e
    if not isinstance(doc, dict) or not isinstance(
            doc.get("traceEvents"), list):
        raise ValueError("trace must be an object with a traceEvents list")
    for i, ev in enumerate(doc["traceEvents"]):
        if not isinstance(ev, dict):
            raise ValueError(f"event {i}: not an object")
        if not isinstance(ev.get("name"), str) or not ev["name"]:
            raise ValueError(f"event {i}: missing name")
        ph = ev.get("ph")
        if ph not in _ALLOWED_PH:
            raise ValueError(f"event {i}: bad phase {ph!r}")
        if "pid" not in ev:
            raise ValueError(f"event {i}: missing pid")
        if ph != "M":
            ts = ev.get("ts")
            if not isinstance(ts, (int, float)) or ts < 0:
                raise ValueError(f"event {i}: bad ts {ts!r}")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                raise ValueError(f"event {i}: bad dur {dur!r}")
        if ph == "C":
            args = ev.get("args")
            if not isinstance(args, dict) or not all(
                    isinstance(v, (int, float)) for v in args.values()):
                raise ValueError(f"event {i}: counter needs numeric args")
