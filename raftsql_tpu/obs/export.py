"""Chrome trace-event export — the JSON object format Perfetto and
chrome://tracing load directly.

One document, three track families:

  * pid 1 "spans": per-group tracks of complete ("X") slices, one per
    adjacent recorded phase pair of every span
    (propose→append→replicate→commit→apply→ack), on the host monotonic
    axis (us since the tracer epoch);
  * pid 2 "host io": the tracer's timeline-event ring (WAL fsyncs, TCP
    frames, ...) as duration slices or instants;
  * pid 3 "device": counter ("C") tracks built from the device event
    ring — commit / inbox depth / vote tally per (peer, group) — on a
    SYNTHETIC tick axis (1 tick = `tick_us` microseconds), since device
    ticks carry no wall clock.  Separate pid, so the axes never mix.

`validate_chrome_trace` is the schema check the tests (and `make
trace`) run over every emitted document, so "Perfetto accepts it" is an
asserted property, not a hope.
"""
from __future__ import annotations

import json
from typing import List, Optional

from raftsql_tpu.obs.spans import PHASES

_ALLOWED_PH = {"X", "B", "E", "i", "I", "C", "M"}


def _meta(pid: int, name: str, tid: Optional[int] = None,
          tname: Optional[str] = None) -> List[dict]:
    out = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": name}}]
    if tid is not None:
        out.append({"name": "thread_name", "ph": "M", "pid": pid,
                    "tid": tid, "args": {"name": tname or str(tid)}})
    return out


def chrome_trace(span_snapshot: Optional[dict] = None,
                 device_rows: Optional[List[dict]] = None,
                 tick_us: float = 1000.0, max_groups: int = 8) -> dict:
    """Build the trace document from `SpanTracer.snapshot()` and/or
    `DeviceEventRing.rows()`.  Either may be None/empty — the document
    is always valid (an empty trace loads fine)."""
    events: List[dict] = []
    events += _meta(1, "raftsql spans")
    seen_groups = set()

    for sp in (span_snapshot or {}).get("spans", ()):
        g = sp["group"]
        if g not in seen_groups and len(seen_groups) < max_groups:
            seen_groups.add(g)
            events += _meta(1, "raftsql spans", tid=g,
                            tname=f"group {g}")[1:]
        ph = sp["phases"]
        stamps = [(name, ph[name]) for name in PHASES if name in ph]
        for (a, ta), (b, tb) in zip(stamps, stamps[1:]):
            events.append({
                "name": f"{a}→{b}", "cat": "span", "ph": "X",
                "ts": ta, "dur": max(tb - ta, 0.0), "pid": 1, "tid": g,
                "args": {"index": sp["index"], "key": sp["key"]}})

    host_events = (span_snapshot or {}).get("events", ())
    if host_events:
        events += _meta(2, "raftsql host io", tid=0, tname="io")
        for ev in host_events:
            rec = {"name": ev["name"], "cat": "io", "ts": ev["ts"],
                   "pid": 2, "tid": 0, "args": ev.get("args", {})}
            if ev.get("dur", 0) > 0:
                rec.update(ph="X", dur=ev["dur"])
            else:
                rec.update(ph="i", s="t")
            events.append(rec)

    if device_rows:
        events += _meta(3, "raftsql device (tick axis)")
        P = len(device_rows[0]["commit"])
        G = min(len(device_rows[0]["commit"][0]), max_groups)
        for row in device_rows:
            ts = row["tick"] * tick_us
            for p in range(P):
                for g in range(G):
                    for field in ("commit", "inbox_depth", "votes"):
                        events.append({
                            "name": f"p{p}/g{g} {field}", "ph": "C",
                            "ts": ts, "pid": 3, "tid": 0,
                            "args": {"value": row[field][p][g]}})

    return {"traceEvents": events, "displayTimeUnit": "ms"}


def validate_chrome_trace(doc: dict) -> None:
    """Raise ValueError unless `doc` is a well-formed Chrome trace-event
    JSON object: serializable, traceEvents a list, every event carrying
    a name, a known phase, a pid, and (for non-metadata phases) a
    non-negative numeric ts; complete events need a non-negative dur,
    counters a numeric value."""
    try:
        json.dumps(doc)
    except (TypeError, ValueError) as e:
        raise ValueError(f"trace not JSON-serializable: {e}") from e
    if not isinstance(doc, dict) or not isinstance(
            doc.get("traceEvents"), list):
        raise ValueError("trace must be an object with a traceEvents list")
    for i, ev in enumerate(doc["traceEvents"]):
        if not isinstance(ev, dict):
            raise ValueError(f"event {i}: not an object")
        if not isinstance(ev.get("name"), str) or not ev["name"]:
            raise ValueError(f"event {i}: missing name")
        ph = ev.get("ph")
        if ph not in _ALLOWED_PH:
            raise ValueError(f"event {i}: bad phase {ph!r}")
        if "pid" not in ev:
            raise ValueError(f"event {i}: missing pid")
        if ph != "M":
            ts = ev.get("ts")
            if not isinstance(ts, (int, float)) or ts < 0:
                raise ValueError(f"event {i}: bad ts {ts!r}")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                raise ValueError(f"event {i}: bad dur {dur!r}")
        if ph == "C":
            args = ev.get("args")
            if not isinstance(args, dict) or not all(
                    isinstance(v, (int, float)) for v in args.values()):
                raise ValueError(f"event {i}: counter needs numeric args")
