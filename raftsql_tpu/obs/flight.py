"""Chaos flight recorder: turn an invariant failure into a post-mortem.

The chaos harness already makes every failure REPRODUCIBLE (the seed
pins the schedule); this makes it READABLE: when an invariant trips,
the runner dumps the last N ticks of device-plane events plus the
host-plane spans — the exact per-tick timeline leading into the
violation — as one JSON artifact next to the failing seed, so a human
(or a later session) starts from a trace, not from a re-run under a
debugger.

The dump directory defaults to the current directory and is overridden
by RAFTSQL_FLIGHT_DIR (tests point it at a tmp dir).  Dump failures
never mask the invariant error — the recorder logs and returns None.
"""
from __future__ import annotations

import json
import logging
import os
import time
from typing import Optional

log = logging.getLogger("raftsql_tpu.obs.flight")


class FlightRecorder:
    def __init__(self, directory: Optional[str] = None,
                 last_ticks: int = 64):
        self.directory = directory or os.environ.get(
            "RAFTSQL_FLIGHT_DIR", ".")
        self.last_ticks = last_ticks

    def dump(self, name: str, reason: str, tracer=None, ring=None,
             meta: Optional[dict] = None, node=None,
             ring_server=None, placement=None) -> Optional[str]:
        """Write flight-<name>.json; returns the path, or None if the
        write failed (never raises — the invariant error must win).

        `node` (a ClusterHostPlane) adds the SERVING-PLANE state the
        post-PR-7 stack crashes with: the double-buffered overlap
        stash's status at crash time (was a durable phase in flight,
        and for which tick?), the WAL group-commit batch histogram,
        and the tick-phase profile — plus the transfer plane's
        in-flight latches and recent outcomes (PR 11).  `ring_server`
        (runtime/ring.py RingServer) adds per-worker propose/completion
        ring cursors and depths.  `placement` (a PlacementController)
        attaches the controller's recent decision log (group, from, to,
        outcome, stall ticks), so a failed transfer invariant is
        attributable to the decision that caused it."""
        doc = {
            "reason": reason,
            "wall_time": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "meta": meta or {},
            "device_events": [],
            "host_spans": {},
        }
        try:
            if ring is not None:
                ring.drain()
                doc["device_events"] = ring.rows(last=self.last_ticks)
            if tracer is not None:
                doc["host_spans"] = tracer.snapshot()
            if node is not None:
                doc["serving"] = self._serving_state(node)
            if ring_server is not None:
                doc.setdefault("serving", {})["rings"] = \
                    ring_server.flight_doc()
            if placement is not None:
                doc["placement"] = placement.doc()
        except Exception as e:      # noqa: BLE001 - diagnostics only
            doc["collect_error"] = repr(e)
        path = os.path.join(self.directory, f"flight-{name}.json")
        try:
            os.makedirs(self.directory, exist_ok=True)
            with open(path, "w", encoding="utf-8") as f:
                json.dump(doc, f, sort_keys=True)
        except OSError as e:
            log.warning("flight-recorder dump to %s failed: %s", path, e)
            return None
        log.warning("flight-recorder dump: %s (%s)", path, reason)
        return path

    @staticmethod
    def _serving_state(node) -> dict:
        """Serving-plane snapshot off a ClusterHostPlane (every field
        getattr-guarded: older/foreign engines just contribute less)."""
        out: dict = {}
        stash = getattr(node, "_stash", None)
        overlap = {"enabled": bool(getattr(node, "_overlap", False)),
                   "stashed": stash is not None}
        if stash is not None:
            try:
                _infos, staged, stick = stash
                overlap["stash_tick"] = int(stick)
                # Entries whose durable phase had NOT yet retired — the
                # exact set a crash at this instant would lose.
                overlap["stash_entries"] = int(sum(
                    len(per_peer[4]) for step in staged
                    for per_peer in step))
            except Exception:       # noqa: BLE001 - diagnostics only
                pass
        out["overlap"] = overlap
        gcw = getattr(node, "_gcwal", None)
        if gcw is not None:
            out["wal_group_commit"] = {
                "group_commits": gcw.group_commits,
                "batch_hist": {str(k): v for k, v in
                               sorted(gcw.batch_hist.items())}}
        prof = getattr(node, "prof", None)
        if prof is not None:
            out["phase_profile"] = prof.snapshot()
        traffic = getattr(node, "traffic", None)
        if traffic is not None:
            xg = getattr(node, "transferring_groups", None)
            out["group_traffic"] = traffic.doc(
                leader_of=getattr(node, "leader_of", None),
                transferring=xg() if callable(xg) else None)
        xfers = getattr(node, "transfers_doc", None)
        if callable(xfers):
            out["transfers"] = xfers()
        return out
