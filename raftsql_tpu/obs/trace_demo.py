"""`make trace` — run a small traced fused cluster and emit a Chrome
trace-event JSON that Perfetto (ui.perfetto.dev) or chrome://tracing
loads directly:

    python -m raftsql_tpu.obs.trace_demo --out trace.json

Drives a 3-peer x G-group FusedClusterNode with tracing enabled for a
few hundred ticks of seeded PUT load, then exports both planes — the
per-proposal lifecycle spans (propose → append → replicate → commit)
and the device event ring's counter tracks (commit / inbox depth /
votes per peer x group) — schema-validates the document
(obs/export.py validate_chrome_trace), and writes it out.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile


def run_demo(out_path: str, groups: int = 4, ticks: int = 200,
             props_per_tick: int = 2) -> dict:
    from raftsql_tpu.config import RaftConfig
    from raftsql_tpu.obs.export import chrome_trace, validate_chrome_trace
    from raftsql_tpu.runtime.fused import FusedClusterNode

    cfg = RaftConfig(num_groups=groups, num_peers=3, log_window=32,
                     max_entries_per_msg=4, election_ticks=10,
                     heartbeat_ticks=1, tick_interval_s=0.0)
    with tempfile.TemporaryDirectory(prefix="raftsql-trace-") as d:
        node = FusedClusterNode(cfg, d)
        node.enable_tracing()
        try:
            seq = 0
            for t in range(ticks):
                if t > 20:           # let the first elections settle
                    for g in range(groups):
                        node.propose_many(
                            g, [f"SET k{g} v{seq + i}".encode()
                                for i in range(props_per_tick)])
                    seq += props_per_tick
                node.tick()
            node.publish_flush()
            node.ring.drain()
            doc = chrome_trace(node.tracer.snapshot(), node.ring.rows())
        finally:
            node.stop()
    validate_chrome_trace(doc)
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    return doc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="trace.json")
    ap.add_argument("--groups", type=int, default=4)
    ap.add_argument("--ticks", type=int, default=200)
    args = ap.parse_args(argv)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    doc = run_demo(args.out, groups=args.groups, ticks=args.ticks)
    n = len(doc["traceEvents"])
    print(f"trace ok: {args.out} ({n} events; load it at "
          f"https://ui.perfetto.dev or chrome://tracing)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
