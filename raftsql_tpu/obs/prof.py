"""Tick-phase profiler: where does a tick's wall time actually go?

`NodeMetrics.phase_ms_per_tick` is a running AVERAGE — it can say "wal
is 40% of the tick" but not "fsync p99 spiked 20x for 50 ticks while
p50 held", which is exactly the shape a serving regression takes.  This
module is the per-phase distribution layer between that average and the
full span tracer: monotonic-clock stamps around each phase of the host
plane's tick —

    pop        proposal pop/stage (_build_prop_n + _stage_ranges)
    dispatch   device dispatch + packed-info readback
    wal_write  WAL entry/hardstate writes (the durable phase minus fsync)
    fsync      the per-peer fsync barrier
    publish    commit delivery to the apply plane
    ring_drain the serving plane's propose-ring drain batches

— ring-buffered per phase (pre-allocated numpy arrays, no allocation
on the hot path, one small lock per record) and exported as p50/p95/p99
phase histograms in `GET /metrics` (`phase_profile`, and as a
Prometheus summary `raftsql_tick_phase_ms{phase=...}` under
`?format=prom`) plus per-phase Perfetto tracks in `GET /trace`.

OVERLAP-AWARE ATTRIBUTION: under double-buffered dispatch
(runtime/hostplane.py, default on) tick t's stashed durable phase
retires inside tick t+1's device window.  Every sample carries the
tick that OWNS the work — the stash remembers its originating tick and
the publish queue items carry theirs — so a phase histogram keyed by
tick is identical whether the pipeline overlaps or not (pinned by
tests/test_obs.py's attribution test).

Default **on** (the per-tick cost is ~10 monotonic reads and ~8 ring
writes — measured ≤2% on the durable bench rung, bench_logs):
RAFTSQL_PROF=0 disables it entirely, RAFTSQL_PROF_SAMPLE=N records
only every Nth tick (the knob for G≫1k deployments where scrape-side
processing of a dense sample stream matters more than the stamps).
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

import numpy as np

# Phases that partition the tick thread's wall time; ring_drain runs on
# the serving plane's drain threads and is reported but excluded from
# the tick-share denominators.
PROF_PHASES = ("pop", "dispatch", "wal_write", "fsync", "publish",
               "ring_drain")
_TICK_PHASES = ("pop", "dispatch", "wal_write", "fsync", "publish")


class TickPhaseProfiler:
    """Per-phase duration rings + totals (see module docstring).

    record() is safe from any thread (tick thread, publish workers,
    ring drains); everything is pre-allocated at construction."""

    def __init__(self, cap: int = 4096, sample: int = 1):
        n = len(PROF_PHASES)
        self.cap = cap
        self.sample = max(1, sample)
        self.epoch = time.monotonic()
        self._i: Dict[str, int] = {p: k for k, p in enumerate(PROF_PHASES)}
        self._dur = np.zeros((n, cap), np.float64)      # seconds
        self._t0 = np.zeros((n, cap), np.float64)       # raw monotonic s
        self._tick = np.full((n, cap), -1, np.int64)    # owning tick
        self._tid = np.zeros((n, cap), np.int32)        # worker/shard id
        self._pos = [0] * n
        self._count = [0] * n
        self._total = [0.0] * n
        self._mu = threading.Lock()

    @classmethod
    def from_env(cls, num_groups: int = 0) -> Optional["TickPhaseProfiler"]:
        """The default-on constructor the host plane uses.  RAFTSQL_PROF=0
        turns the profiler off; RAFTSQL_PROF_SAMPLE=N samples 1-in-N
        ticks; RAFTSQL_PROF_CAP sizes the per-phase rings."""
        if os.environ.get("RAFTSQL_PROF", "1") == "0":
            return None
        cap = int(os.environ.get("RAFTSQL_PROF_CAP", "4096"))
        sample = int(os.environ.get("RAFTSQL_PROF_SAMPLE", "1") or 1)
        return cls(cap=max(64, cap), sample=sample)

    def sampled(self, tick_no: int) -> bool:
        """Whether this tick's phases should be stamped (the 1-in-N
        sampling gate — callers skip even the monotonic reads when
        False)."""
        return self.sample <= 1 or tick_no % self.sample == 0

    def record(self, phase: str, tick_no: int, t_start: float,
               dur_s: float, tid: int = 0) -> None:
        k = self._i[phase]
        with self._mu:
            j = self._pos[k]
            self._dur[k, j] = dur_s
            self._t0[k, j] = t_start
            self._tick[k, j] = tick_no
            self._tid[k, j] = tid
            self._pos[k] = (j + 1) % self.cap
            self._count[k] += 1
            self._total[k] += dur_s

    # -- export ---------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-ready per-phase histograms over the ring window:
        {phase: {p50_ms, p95_ms, p99_ms, max_ms, n, total_ms}} plus the
        sampling factor.  Sorting happens OUTSIDE the lock (the scrape
        must never stall the tick thread's record)."""
        with self._mu:
            durs = self._dur.copy()
            ticks = self._tick.copy()
            counts = list(self._count)
            totals = list(self._total)
        out: dict = {"sample": self.sample}
        for p, k in self._i.items():
            if not counts[k]:
                continue
            valid = durs[k][ticks[k] >= 0]
            valid.sort()
            n = valid.size

            def q(f):
                return round(float(valid[min(int(f * n), n - 1)]) * 1e3,
                             4)

            out[p] = {"p50_ms": q(0.5), "p95_ms": q(0.95),
                      "p99_ms": q(0.99),
                      "max_ms": round(float(valid[-1]) * 1e3, 4),
                      "n": counts[k],
                      "total_ms": round(totals[k] * 1e3, 3)}
        return out

    def shares(self) -> dict:
        """Each tick phase's share of the total profiled tick time —
        the one-line "why did this rung move" summary the durable bench
        records (fsync-share vs dispatch-share vs publish-share)."""
        with self._mu:
            totals = {p: self._total[self._i[p]] for p in _TICK_PHASES}
        denom = sum(totals.values())
        if denom <= 0:
            return {f"{p}_share": 0.0 for p in _TICK_PHASES}
        return {f"{p}_share": round(v / denom, 4)
                for p, v in totals.items()}

    def phase_ticks(self, phase: str) -> List[int]:
        """Sorted distinct tick ids holding samples of `phase` (the
        attribution test's probe)."""
        k = self._i[phase]
        with self._mu:
            t = self._tick[k].copy()
        return sorted(set(int(x) for x in t[t >= 0]))

    def events(self, last: int = 2048) -> List[dict]:
        """The ring window as Perfetto-ready phase events (newest-last,
        RAW monotonic start seconds — the caller rebases to its trace
        epoch): {"phase", "tick", "t0", "dur", "tid"}."""
        with self._mu:
            durs = self._dur.copy()
            t0s = self._t0.copy()
            ticks = self._tick.copy()
            tids = self._tid.copy()
        evs: List[dict] = []
        for p, k in self._i.items():
            m = ticks[k] >= 0
            for t0, d, tk, td in zip(t0s[k][m], durs[k][m],
                                     ticks[k][m], tids[k][m]):
                evs.append({"phase": p, "tick": int(tk),
                            "t0": float(t0), "dur": float(d),
                            "tid": int(td)})
        evs.sort(key=lambda e: e["t0"])
        return evs[-last:]
