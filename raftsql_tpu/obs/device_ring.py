"""Tick-indexed on-device event ring for the fused cluster runtimes.

The fused runtime's consensus math is opaque to the host: one dispatch
advances P peers x G groups and the host sees only the packed StepInfo
it needs for durability.  `NodeMetrics` aggregates further, to run
totals.  This ring is the per-tick history between those extremes: a
fixed-shape [depth, P, G, NEV] i32 array living ON DEVICE, written one
slot per tick by a single small fused program (`_record_slot`), and
drained to the host in whole-ring batches — so with tracing enabled the
per-tick cost is one extra dispatch over already-resident arrays, and
one device_get every `depth` ticks; with tracing disabled (the
default) nothing here runs and the step signatures
(core/cluster.py cluster_step_host / cluster_multistep_host) are
untouched.

Per (peer, group) the slot records (EVENT_FIELDS order): the tick
number, term, role, leader hint, commit index, host applied index (the
host's pre-publish cursor, passed in), device log length, inbox depth
(message slots in flight to the NEXT tick — the post-step delivered
inbox), and the vote tally.  Everything the chaos post-mortems and the
Perfetto counter tracks need to say WHY a tick behaved as it did.
"""
from __future__ import annotations

import functools
import threading
from collections import deque
from typing import List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from raftsql_tpu.core.step import INFO_FIELDS

EVENT_FIELDS = ("tick", "term", "role", "leader", "commit", "applied",
                "log_len", "inbox_depth", "votes")
NEV = len(EVENT_FIELDS)

_C = {n: i for i, n in enumerate(INFO_FIELDS)}


@functools.partial(jax.jit, donate_argnums=(0,))
def _record_slot(ring: jax.Array, slot: jax.Array, tick_no: jax.Array,
                 pinfo: jax.Array, votes: jax.Array, v_type: jax.Array,
                 a_type: jax.Array, applied: jax.Array) -> jax.Array:
    """Write one [P, G, NEV] event row into ring[slot].

    pinfo is the step's packed [P, G, INFO_NCOLS] info (the final step
    of a multi-step dispatch); votes/v_type/a_type are the post-step
    stacked state/inbox leaves; applied is the host's [P, G] apply
    cursor.  All reads are masks/stacks over resident arrays and the
    write is one dynamic_update_slice — no gathers, no scatters
    (ops/dense.py's TPU rule), so the traced tick stays cheap.
    """
    depth = ((v_type != 0).sum(-1) + (a_type != 0).sum(-1))     # [P, G]
    nvotes = votes.sum(-1)                                      # [P, G]
    tick_col = jnp.broadcast_to(jnp.asarray(tick_no, jnp.int32),
                                depth.shape)
    ev = jnp.stack([tick_col,
                    pinfo[:, :, _C["term"]],
                    pinfo[:, :, _C["role"]],
                    pinfo[:, :, _C["leader_hint"]],
                    pinfo[:, :, _C["commit"]],
                    applied,
                    pinfo[:, :, _C["new_log_len"]],
                    depth, nvotes], axis=-1).astype(jnp.int32)
    return jax.lax.dynamic_update_slice_in_dim(ring, ev[None], slot,
                                               axis=0)


class DeviceEventRing:
    """Host manager for the on-device ring: owns the device array, the
    write cursor, and the drained host-side history (a bounded deque of
    [P, G, NEV] numpy rows, newest last)."""

    def __init__(self, num_peers: int, num_groups: int,
                 depth: int = 64, keep: int = 4096):
        self.depth = depth
        self._ring = jnp.zeros((depth, num_peers, num_groups, NEV),
                               jnp.int32)
        self._slot = 0
        self._events: deque = deque(maxlen=keep)
        self.drains = 0
        # record() runs on the tick thread; drain()/rows() also run on
        # scrape threads (GET /trace, GET /events, the flight
        # recorder).  The lock serializes ring/cursor/deque access —
        # contention is one scrape against one tick, never tick-tick.
        self._mu = threading.Lock()

    def record(self, tick_no: int, pinfo_dev, votes, v_type, a_type,
               applied: np.ndarray) -> None:
        """Record one tick's events; auto-drains when the ring fills."""
        with self._mu:
            self._ring = _record_slot(
                self._ring, jnp.asarray(self._slot, jnp.int32),
                jnp.asarray(tick_no, jnp.int32), pinfo_dev, votes,
                v_type, a_type, jnp.asarray(applied.astype(np.int32)))
            self._slot += 1
            if self._slot >= self.depth:
                self._drain_locked()

    def drain(self) -> None:
        """Pull every undrained slot to the host (ONE device_get)."""
        with self._mu:
            self._drain_locked()

    def _drain_locked(self) -> None:
        if self._slot == 0:
            return
        host = np.asarray(jax.device_get(self._ring))[:self._slot]
        self._events.extend(host)
        self._slot = 0
        self.drains += 1

    def rows(self, last: Optional[int] = None) -> List[dict]:
        """Drained history as JSON-ready per-tick dicts (newest-last):
        {"tick": t, "<field>": [[G values] per peer], ...}.  Call
        drain() first for up-to-the-tick data."""
        with self._mu:
            events = list(self._events)
        if last is not None:
            events = events[-last:]
        out = []
        for row in events:                       # row: [P, G, NEV]
            d = {"tick": int(row[0, 0, 0])}
            for i, name in enumerate(EVENT_FIELDS):
                if name != "tick":
                    d[name] = row[:, :, i].tolist()
            out.append(d)
        return out

    def __len__(self) -> int:
        return len(self._events)
