"""Observability subsystem: device-plane event rings, host-plane span
tracing, Chrome-trace (Perfetto) export, and the chaos flight recorder.

Two planes, matching the engine's own split:

  * DEVICE plane (`device_ring.py`): a tick-indexed on-device event
    ring — a fixed-shape [depth, P, G, NEV] i32 array the fused runtime
    writes one slot per tick (one tiny fused dispatch; no host
    round-trip), drained to the host in whole-ring batches so the
    steady-state cost is ~one device_get per `depth` ticks.
  * HOST plane (`spans.py`): a span tracer following each proposal
    through its lifecycle (propose → WAL append → replicate → quorum →
    commit → apply → ack) with monotonic timestamps, plus a generic
    timeline-event ring for WAL fsyncs, TCP frames, and anything else
    the host planes want on the trace.

Exports (`export.py`): Chrome trace-event JSON loadable in Perfetto
(`make trace`, `GET /trace`), raw event JSON (`GET /events`).  The
chaos harness wires both planes into a flight recorder (`flight.py`):
an invariant failure dumps the last N ticks of device events plus the
host spans next to the failing seed.

TRACING is OFF by default: the engine carries a `tracer`/`ring`
attribute that is None until `enable_tracing()` is called, and every
hook is gated on that attribute — the disabled cost is one attribute
test, and the fused scan signatures are untouched.

The production TELEMETRY plane is ON by default (it is cheap enough to
be): the tick-phase profiler (`prof.py` — per-phase p50/p95/p99 of
where the tick's wall time goes, overlap-aware, RAFTSQL_PROF=0 to
disable) and the per-group traffic accounting
(utils/metrics.py GroupTraffic — `[G]` propose/commit/ack counters +
EWMA rates feeding the /metrics top-K hot-groups table).  Both are
pure observers: chaos digests are pinned bit-identical with them on.
Cross-process trace SEGMENTS (`export.py TraceSegmentWriter`) let
`--workers N` HTTP worker processes land on the engine's /trace as one
merged multi-process Perfetto timeline.
"""
from raftsql_tpu.obs.device_ring import EVENT_FIELDS, DeviceEventRing
from raftsql_tpu.obs.export import (TraceSegmentWriter, chrome_trace,
                                    collect_segments,
                                    validate_chrome_trace)
from raftsql_tpu.obs.flight import FlightRecorder
from raftsql_tpu.obs.prof import PROF_PHASES, TickPhaseProfiler
from raftsql_tpu.obs.spans import SpanTracer

__all__ = ["EVENT_FIELDS", "DeviceEventRing", "SpanTracer",
           "chrome_trace", "validate_chrome_trace", "FlightRecorder",
           "TickPhaseProfiler", "PROF_PHASES", "TraceSegmentWriter",
           "collect_segments"]
