"""Host-plane span tracer: per-proposal lifecycle with monotonic stamps.

A span follows one client proposal through the pipeline the engine
actually runs:

    propose -> append (leader WAL) -> replicate (follower mirror/send)
            -> commit (quorum; the two coincide at the leader)
            -> apply -> ack

Stamps come from the planes that own each transition (runtime/db.py,
runtime/node.py, runtime/fused.py); the tracer only correlates them.
Correlation is two-stage, mirroring the engine's own identity scheme:
before an index is assigned, spans wait in a per-group FIFO keyed by
payload content (the same content-FIFO identity the ack router uses,
SURVEY.md §2d.3); the leader-append hook then binds each accepted
payload to its log index, and every later phase stamps by
(group, index).  Forwarded/replayed entries with no local span are
skipped — tracing is an observer, never a participant.

Everything is bounded: pending and live spans are capped (oldest spill
to the completed ring), completed spans and timeline events live in
`deque(maxlen=...)` rings — a tracer left on forever holds a constant
footprint.  All methods take one small lock; callers gate on
`tracer is not None`, so the disabled cost is one attribute test.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

PHASES = ("propose", "append", "replicate", "commit", "apply", "ack")

# Bounded watermark walk per note_replicate/note_commit call: commit can
# jump arbitrarily far after a catch-up; spans beyond the cap simply
# miss the stamp (observability degrades, never the tick).
_WALK_CAP = 4096


class Span:
    __slots__ = ("group", "key", "index", "t")

    def __init__(self, group: int, key: str, t_propose: float):
        self.group = group
        self.key = key
        self.index = -1
        self.t: Dict[str, float] = {"propose": t_propose}

    def as_dict(self, t0: float) -> dict:
        return {"group": self.group, "key": self.key[:128],
                "index": self.index,
                "phases": {k: round((v - t0) * 1e6, 1)   # us since epoch
                           for k, v in self.t.items()}}


class SpanTracer:
    def __init__(self, max_pending: int = 4096, max_live: int = 8192,
                 max_done: int = 4096, max_events: int = 8192):
        self.t0 = time.monotonic()
        self._mu = threading.Lock()
        self._pending: Dict[int, deque] = {}       # group -> [Span]
        self._by_index: Dict[Tuple[int, int], Span] = {}
        self._live_fifo: deque = deque()           # (g, idx) insertion order
        self._by_key: Dict[Tuple[int, str], deque] = {}
        self._done: deque = deque(maxlen=max_done)
        self._events: deque = deque(maxlen=max_events)
        self._marks: Dict[Tuple[str, int], int] = {}   # (phase, g) -> idx
        self._max_pending = max_pending
        self._max_live = max_live
        self.dropped = 0

    # -- lifecycle hooks ------------------------------------------------

    def begin(self, group: int, key: str) -> None:
        """A client proposal entered the pipeline (pre-index)."""
        now = time.monotonic()
        with self._mu:
            q = self._pending.setdefault(group, deque())
            if len(q) >= self._max_pending:
                q.popleft()
                self.dropped += 1
            q.append(Span(group, key, now))

    def note_append(self, group: int, start: int, keys: List[str]) -> None:
        """The leader accepted `keys` into its log at start..start+n-1
        and wrote them to the WAL: bind indexes, stamp `append`.
        Payloads with no pending span (forwarded from a peer, replays)
        are skipped."""
        now = time.monotonic()
        with self._mu:
            q = self._pending.get(group)
            if not q:
                return
            for off, key in enumerate(keys):
                sp = None
                for cand in q:
                    if cand.key == key:
                        sp = cand
                        break
                if sp is None:
                    continue
                q.remove(sp)
                sp.index = start + off
                sp.t["append"] = now
                self._by_index[(group, sp.index)] = sp
                self._live_fifo.append((group, sp.index))
                self._by_key.setdefault((group, key), deque()).append(sp)
            while len(self._by_index) > self._max_live:
                self._evict_oldest_locked()

    def _evict_oldest_locked(self) -> None:
        while self._live_fifo:
            k = self._live_fifo.popleft()
            sp = self._by_index.pop(k, None)
            if sp is not None:
                self._finish_locked(sp)
                return

    def _stamp_upto(self, phase: str, group: int, upto: int,
                    also: Optional[str] = None) -> None:
        now = time.monotonic()
        with self._mu:
            mark = self._marks.get((phase, group), 0)
            if upto <= mark:
                return
            lo = max(mark + 1, upto - _WALK_CAP + 1)
            for idx in range(lo, upto + 1):
                sp = self._by_index.get((group, idx))
                if sp is None:
                    continue
                sp.t.setdefault(phase, now)
                if also is not None:
                    sp.t.setdefault(also, now)
            self._marks[(phase, group)] = upto

    def note_replicate(self, group: int, upto: int) -> None:
        """Entries up to `upto` were handed to a follower (fused: the
        mirror landed in the follower's log; distributed: the append
        left on the wire)."""
        self._stamp_upto("replicate", group, upto)

    def note_commit(self, group: int, upto: int) -> None:
        """The group's commit index reached `upto` — the quorum point.
        Implies replication, so a missing replicate stamp is filled."""
        self._stamp_upto("commit", group, upto, also="replicate")

    def note_apply(self, group: int, index: int) -> None:
        now = time.monotonic()
        with self._mu:
            sp = self._by_index.get((group, index))
            if sp is not None:
                sp.t.setdefault("apply", now)

    def note_ack(self, group: int, key: str) -> None:
        """The client ack fired (content-FIFO identity, matching the
        ack router): finalize the oldest live span with this key."""
        now = time.monotonic()
        with self._mu:
            q = self._by_key.get((group, key))
            if not q:
                return
            sp = q.popleft()
            if not q:
                del self._by_key[(group, key)]
            sp.t["ack"] = now
            self._by_index.pop((group, sp.index), None)
            self._finish_locked(sp)

    def _finish_locked(self, sp: Span) -> None:
        q = self._by_key.get((sp.group, sp.key))
        if q is not None:
            try:
                q.remove(sp)
            except ValueError:
                pass
            if not q:
                self._by_key.pop((sp.group, sp.key), None)
        self._done.append(sp)

    # -- generic timeline events ---------------------------------------

    def note_event(self, name: str, dur_s: float = 0.0,
                   t_start: Optional[float] = None, **args) -> None:
        """A point or duration event on the host timeline (WAL fsync,
        TCP frame, tick phase, ...)."""
        t = time.monotonic() - dur_s if t_start is None else t_start
        with self._mu:      # snapshot() iterates this deque
            self._events.append((name, t, dur_s, args))

    # -- export ---------------------------------------------------------

    def snapshot(self, max_spans: int = 4096) -> dict:
        """JSON-ready view: completed + still-live spans (us-since-epoch
        stamps) and the timeline-event ring."""
        with self._mu:
            done = list(self._done)
            live = list(self._by_index.values())
            events = list(self._events)
        spans = [sp.as_dict(self.t0) for sp in (done + live)[-max_spans:]]
        evs = [{"name": n, "ts": round((t - self.t0) * 1e6, 1),
                "dur": round(d * 1e6, 1), "args": a}
               for (n, t, d, a) in events]
        return {"epoch_monotonic": self.t0, "spans": spans,
                "events": evs, "dropped": self.dropped}
