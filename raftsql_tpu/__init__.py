"""raftsql_tpu — a TPU-native multi-raft replicated-SQL framework.

Brand-new implementation of the capabilities of chzchzchz/raftsql (a SQLite
database replicated by raft, served over HTTP PUT/GET): N co-located raft
groups advance in lock-step batched JAX device steps, host code owns WAL
durability, SQL apply, and transport.  See SURVEY.md for the capability
contract derived from the reference.
"""

from raftsql_tpu.config import RaftConfig

__all__ = ["RaftConfig"]
__version__ = "0.1.0"
