"""Standalone read replica: subscribe to an engine's --replica-listen
stream and serve the read ladder over either HTTP plane.

    python -m raftsql_tpu.replica --upstream host:9220 --port 9221

The process is read-only by construction: PUT/POST answer 421 with the
upstream leader hint.  --advertise names the HTTP endpoint published
back to the engine (the client sweep adopts it from the engine's
/healthz `replica.endpoints`); it defaults to 127.0.0.1:<port> for
single-box deployments.  --unsafe-serve exists ONLY as the chaos
falsification seam (make chaos-replica): it disables the session and
linear fail-closed gates so the StaleReadNever invariant can prove it
would have caught a stale-serving replica.
"""
from __future__ import annotations

import argparse
import logging
import sys

from raftsql_tpu.replica.node import ReplicaDB, ReplicaSubscriber
from raftsql_tpu.replica.stream import parse_hostport

log = logging.getLogger("raftsql.replica")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m raftsql_tpu.replica",
        description="read replica: stream subscriber + HTTP read plane")
    ap.add_argument("--upstream", required=True,
                    help="engine --replica-listen endpoint, host:port")
    ap.add_argument("--port", type=int, default=9221,
                    help="HTTP port to serve reads on")
    ap.add_argument("--host", default="", help="HTTP bind host")
    ap.add_argument("--advertise", default="",
                    help="endpoint to publish to the engine's /healthz "
                         "(default 127.0.0.1:<port>)")
    ap.add_argument("--http-engine", choices=("threaded", "aio"),
                    default="threaded")
    ap.add_argument("--timeout", type=float, default=30.0,
                    help="per-request timeout seconds")
    ap.add_argument("--unsafe-serve", action="store_true",
                    help="DANGEROUS: disable the session/linear "
                         "fail-closed gates (chaos falsification only)")
    ap.add_argument("--write-cap", type=int, default=0,
                    help="bound on concurrent write-fallback redirects "
                         "(excess answers 429 + Retry-After; 0 = "
                         "unbounded)")
    args = ap.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")

    advertise = args.advertise or f"127.0.0.1:{args.port}"
    sub = ReplicaSubscriber(parse_hostport(args.upstream),
                            advertise=advertise)
    sub.start()
    rdb = ReplicaDB(sub, unsafe_serve=args.unsafe_serve,
                    write_cap=args.write_cap)
    if args.unsafe_serve:
        log.warning("UNSAFE-SERVE: session/linear gates disabled — "
                    "chaos falsification mode, never production")
    # Reuse the server's SIGTERM/SIGINT plumbing: clean stop closes the
    # HTTP plane, then the subscriber + state machines.
    from raftsql_tpu.server.main import _install_graceful_shutdown
    if args.http_engine == "aio":
        from raftsql_tpu.api.aio import AioSQLServer
        srv = AioSQLServer(args.port, rdb, host=args.host,
                           timeout_s=args.timeout)
    else:
        from raftsql_tpu.api.http import SQLServer
        srv = SQLServer(args.port, rdb, host=args.host,
                        timeout_s=args.timeout)
    _install_graceful_shutdown(rdb, srv.stop)
    log.info("replica serving on :%d (upstream %s, %s plane)",
             args.port, args.upstream, args.http_engine)
    srv.serve_forever()
    return 0


if __name__ == "__main__":
    sys.exit(main())
