"""Replica side of the read-replica tier: subscriber + facade.

`ReplicaSubscriber` maintains the upstream connection: subscribe with
the high-water {group: applied} resume vector, fold REC frames into
per-group in-memory SQLite replicas exactly as the shm reader's
_catch_up does (KIND_BASE installs only above the local applied index;
KIND_DELTA rides the resume-mode state machine's `index <= applied`
dedup, so replays and re-images are idempotent), track the TABLE
heartbeat (watermark / lease / leader columns, with the lease deadline
re-based onto the replica's own CLOCK_MONOTONIC from the wire's
*remaining* nanoseconds — conservatively early by the one-way
latency), and reconnect with backoff on any error.  A corrupt frame
(CRC mismatch) poisons the connection — framing can't be re-trusted
past the first bad byte — so the subscriber counts it, drops, and
resubscribes; the publisher replays or re-images from the vector.

`ReplicaDB` fronts the subscriber with the same facade surface RaftDB
gives both HTTP planes, so api/http.py and api/aio.py serve a replica
process UNCHANGED.  The read ladder is the shm reader's, transplanted
— and every unprovable mode FAILS CLOSED as a 421 (`ReplicaRefusal`,
a NotLeaderError) carrying the upstream leader hint, pointing the
client back at the authoritative tier:

  * any mode    — refused until the stream has attached (epoch 0);
  * local       — the replica's current fold: arbitrary staleness is
                  this mode's documented contract, served always;
  * session     — refused unless the folded applied index covers the
                  client's X-Raft-Session watermark within a short
                  bounded wait (the engine BLOCKS authoritatively; a
                  WAN replica refuses fast so the client falls back);
  * follower    — refused unless the TABLE heartbeat is fresh
                  (PUB_STALE_NS) and the fold covers the upstream
                  commit watermark it carries;
  * linear      — stream-ReadIndex: wait for a TABLE received AFTER
                  the request arrived (the commit point it carries is
                  then >= every write acked before the read began),
                  require the leader lease to cover local now and the
                  heartbeat to be fresh, wait for the fold to reach
                  that commit point, re-check the lease at serve time.
                  The Paxos-vs-Raft survey's lease envelope, with the
                  one-way-latency-early local deadline as margin.

Proposals, membership changes and transfers refuse with the same 421:
the replica tier is read-only by construction.

`--unsafe-serve` (chaos falsification ONLY) disables the session and
linear gates: the replica then serves below acked watermarks and past
its lease horizon, and `make chaos-replica`'s StaleReadNever invariant
MUST catch it.
"""
from __future__ import annotations

import json
import socket
import threading
import time
from typing import Dict, Optional, Tuple

from raftsql_tpu.models.sqlite_sm import SQLiteStateMachine, is_select
from raftsql_tpu.overload import Overloaded, zero_metrics_doc
from raftsql_tpu.replica import stream as wire
from raftsql_tpu.runtime.db import NotLeaderError
from raftsql_tpu.runtime.shm import KIND_BASE, KIND_DELTA, PUB_STALE_NS

ACK_INTERVAL_S = 0.05
RECONNECT_DELAY_S = 0.1
# Bound on every ladder gate wait: a WAN replica refuses FAST and lets
# the client fall back to the write tier rather than burning the
# client's deadline blocking the way the engine (authoritatively) may.
GATE_WAIT_S = 0.25

_MODES = ("local", "session", "follower", "linear")


class ReplicaRefusal(NotLeaderError):
    """A fail-closed ladder refusal: 421 + the upstream leader hint.
    Subclasses NotLeaderError so both HTTP planes' existing handlers
    route it; `reason` names the failed gate for counters and logs."""

    def __init__(self, group: int, leader: int, reason: str):
        Exception.__init__(
            self, f"group {group}: replica refuses ({reason})"
            + (f"; leader is node {leader}" if leader > 0 else ""))
        self.group = group
        self.leader = leader
        self.reason = reason


class ReplicaSubscriber:
    """Owns the upstream connection and the folded per-group state.

    All folded state (_sms, _tbl, epoch columns, counters) is guarded
    by _cond's lock; the fold thread notifies it on every applied
    advance and TABLE arrival so ladder gates can wait without
    polling."""

    def __init__(self, upstream: Tuple[str, int], advertise: str = ""):
        self.upstream = upstream
        self.advertise = advertise
        self._cond = threading.Condition()
        self._sms: Dict[int, SQLiteStateMachine] = {}  # raftlint: guarded-by=_cond
        self.epoch = 0               # 0 = never attached: refuse all
        self.keymap_epoch = 0
        self.num_groups = 0
        self.connected = False
        self._tbl: Optional[dict] = None   # rx_ns, log_full, rows
        self.bytes_rx = 0
        self.recs_rx = 0
        self.bases_rx = 0
        self.resyncs = 0             # epoch resets + re-images over state
        self.corrupt_frames = 0
        self.connects = 0
        self._stop = threading.Event()
        self._sock: Optional[socket.socket] = None
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="replica-subscribe")

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        sock = self._sock
        if sock is not None:
            # shutdown BEFORE close: close() alone leaves the fold
            # thread parked in recv() (the in-flight syscall pins the
            # file description on Linux) — shutdown delivers the EOF.
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        self._thread.join(timeout=5)

    # -- connection loop -------------------------------------------------

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self._session()
            except wire.StreamCorruptError:
                # Poisoned framing: count, drop, resubscribe with the
                # resume vector.  Never fold past the first bad byte.
                with self._cond:
                    self.corrupt_frames += 1
            except (wire.StreamClosed, OSError, ValueError):
                pass
            finally:
                with self._cond:
                    self.connected = False
                    self._cond.notify_all()
            self._stop.wait(RECONNECT_DELAY_S)

    def _session(self) -> None:
        sock = socket.create_connection(self.upstream, timeout=5.0)
        self._sock = sock
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sock.settimeout(None)
            kind, body = wire.read_frame(sock)
            if kind != wire.K_HELLO:
                raise wire.StreamClosed("expected HELLO")
            hello = wire.decode_hello(body)
            with self._cond:
                if self.epoch and hello["epoch"] != self.epoch:
                    # New engine incarnation: the shm reader's stale-
                    # epoch rule, with refold instead of death — the
                    # stream re-images us from the new log.
                    self._sms.clear()
                    self._tbl = None
                    self.resyncs += 1
                self.epoch = hello["epoch"]
                self.keymap_epoch = hello["keymap_epoch"]
                self.num_groups = max(self.num_groups, hello["groups"])
                resume = {g: sm.applied_index()
                          for g, sm in self._sms.items()}
            sock.sendall(wire.encode_subscribe(self.advertise, resume))
            with self._cond:
                self.connected = True
                self.connects += 1
                self._cond.notify_all()
            last_ack = 0.0
            while not self._stop.is_set():
                kind, body = wire.read_frame(sock)
                with self._cond:
                    self.bytes_rx += len(body) + 9
                if kind == wire.K_REC:
                    self._fold_rec(*wire.decode_rec(body))
                elif kind == wire.K_TABLE:
                    self._fold_table(body)
                now = time.monotonic()
                if now - last_ack >= ACK_INTERVAL_S:
                    with self._cond:
                        acked = {g: sm.applied_index()
                                 for g, sm in self._sms.items()}
                    sock.sendall(wire.encode_ack(acked))
                    last_ack = now
        finally:
            self._sock = None
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass

    # -- folding ---------------------------------------------------------

    def _fold_rec(self, kind: int, group: int, index: int,
                  payload: bytes) -> None:
        with self._cond:
            sm = self._sms.get(group)
            if sm is None:
                sm = SQLiteStateMachine(":memory:", resume=True)
                self._sms[group] = sm
            self.recs_rx += 1
            if kind == KIND_BASE:
                if index > sm.applied_index():
                    if sm.applied_index() > 0:
                        self.resyncs += 1    # re-imaged over live state
                    sm.install(payload, index)
                    self.bases_rx += 1
            elif kind == KIND_DELTA:
                # resume-mode state machine skips index <= applied —
                # replay and tee overlap are harmless, exactly as in
                # ShmSnapshotReader._catch_up.
                sm.apply(payload.decode("utf-8"), index)
            self._cond.notify_all()

    def _fold_table(self, body: bytes) -> None:
        epoch, keymap_epoch, log_full, rows = wire.decode_table(body)
        now = time.monotonic_ns()
        local = []
        for applied, commit, base, remaining, leader in rows:
            # Re-base the lease onto OUR monotonic clock: early by the
            # one-way latency, never late.
            lease_local = now + remaining if remaining > 0 else 0
            local.append((applied, commit, base, lease_local, leader))
        with self._cond:
            if self.epoch and epoch != self.epoch:
                raise wire.StreamClosed("epoch changed mid-stream")
            self.keymap_epoch = keymap_epoch
            self.num_groups = max(self.num_groups, len(local))
            self._tbl = {"rx_ns": now, "log_full": log_full,
                         "rows": local}
            self._cond.notify_all()

    # -- folded-state accessors (callers hold _cond) ---------------------

    def applied_locked(self, group: int) -> int:
        sm = self._sms.get(group)
        return int(sm.applied_index()) if sm is not None else 0

    def leader_locked(self, group: int) -> int:
        tbl = self._tbl
        if tbl is None or not 0 <= group < len(tbl["rows"]):
            return 0
        return int(tbl["rows"][group][4])

    def heartbeat_age_ns_locked(self) -> int:
        if self._tbl is None:
            return 1 << 62
        return time.monotonic_ns() - self._tbl["rx_ns"]

    def wait_applied_locked(self, group: int, target: int,
                            deadline: float) -> bool:
        while self.applied_locked(group) < target:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            self._cond.wait(remaining)
        return True

    def wait_table_after_locked(self, t0_ns: int,
                                deadline: float) -> Optional[dict]:
        while self._tbl is None or self._tbl["rx_ns"] <= t0_ns:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            self._cond.wait(remaining)
        return self._tbl


class ReplicaDB:
    """The RaftDB facade over a ReplicaSubscriber: both HTTP planes
    serve it unchanged.  Reads run the fail-closed ladder; every write
    or admin verb refuses 421 toward the authoritative tier."""

    def __init__(self, sub: ReplicaSubscriber, unsafe_serve: bool = False,
                 write_cap: int = 0):
        self.sub = sub
        self.unsafe_serve = unsafe_serve
        self.reshard = None          # /kv and POST /reshard answer 503
        self.placement = None
        self._mu = threading.Lock()
        self.hits = {m: 0 for m in _MODES}      # raftlint: guarded-by=_mu
        self.refusals: Dict[str, int] = {}      # raftlint: guarded-by=_mu
        # Write-fallback admission: the redirect lookup contends the
        # fold lock, so a misdirected-write stampede must be shed (429
        # + Retry-After) before it queues unboundedly on _cond and
        # starves the subscriber.  0 = unbounded (seed behaviour).
        self.write_cap = int(write_cap)
        self._write_inflight = 0                # raftlint: guarded-by=_mu
        self._overloaded = 0                    # raftlint: guarded-by=_mu
        self._closed = False

    @property
    def num_groups(self) -> int:
        return max(1, self.sub.num_groups)

    # -- the read ladder -------------------------------------------------

    def _refuse(self, group: int, leader: int, reason: str):
        with self._mu:
            self.refusals[reason] = self.refusals.get(reason, 0) + 1
        raise ReplicaRefusal(group, leader, reason)

    # raftlint: fail-closed
    def query(self, query: str, group: int = 0, linear: bool = False,
              timeout: float = 10.0, mode: Optional[str] = None,
              watermark: int = 0,
              deadline_ms: Optional[float] = None, brownout: bool = False,
              info: Optional[dict] = None) -> str:
        if not is_select(query):
            raise ValueError("replica tier is read-only (expected SELECT)")
        mode = (mode or ("linear" if linear else "local")).lower()
        if mode not in _MODES:
            raise ValueError(f"unknown consistency mode {mode!r}")
        if deadline_ms is not None:
            # The caller's end-to-end budget caps every gate wait; the
            # plane already shed <=0 at the edge.
            timeout = min(float(timeout or GATE_WAIT_S),
                          max(deadline_ms / 1000.0, 0.0))
        if info is not None:
            # The replica never silently downgrades: the mode asked for
            # is the mode served (a failed gate refuses instead), so the
            # served-mode contract header is just the request mode.
            info["served"] = mode
        sub = self.sub
        bound = max(0.01, min(float(timeout or GATE_WAIT_S), GATE_WAIT_S))
        with sub._cond:
            leader = sub.leader_locked(group)
            if sub.epoch == 0:
                self._refuse(group, leader, "no-stream")
            if group < 0 or (sub.num_groups and group >= sub.num_groups):
                raise ValueError(f"group {group} out of range")
            deadline = time.monotonic() + bound
            if mode == "session" and not self.unsafe_serve:
                if not sub.wait_applied_locked(group, watermark, deadline):
                    self._refuse(group, sub.leader_locked(group),
                                 "watermark-uncovered")
            elif mode == "follower":
                if sub.heartbeat_age_ns_locked() > PUB_STALE_NS:
                    self._refuse(group, leader, "heartbeat-stale")
                commit = sub._tbl["rows"][group][1]
                if not sub.wait_applied_locked(group, commit, deadline):
                    self._refuse(group, sub.leader_locked(group),
                                 "apply-lag")
            elif mode == "linear" and not self.unsafe_serve:
                # Stream-ReadIndex: a TABLE received after t0 carries a
                # commit point >= every write acked before this read
                # began; folding to it under a live lease gives the
                # leader-lease linearizability envelope.
                t0 = time.monotonic_ns()
                tbl = sub.wait_table_after_locked(t0, deadline)
                if tbl is None:
                    self._refuse(group, leader, "heartbeat-stale")
                applied_pub, commit, _b, lease, _l = tbl["rows"][group]
                if lease <= 0 or time.monotonic_ns() >= lease:
                    self._refuse(group, sub.leader_locked(group),
                                 "lease-lapsed")
                if not sub.wait_applied_locked(group, commit, deadline):
                    self._refuse(group, sub.leader_locked(group),
                                 "apply-lag")
                # Re-check at serve time: the wait may have outlived
                # the lease that justified the read point.
                tbl = sub._tbl
                if tbl is None \
                        or sub.heartbeat_age_ns_locked() > PUB_STALE_NS:
                    self._refuse(group, sub.leader_locked(group),
                                 "heartbeat-stale")
                lease_now = tbl["rows"][group][3]
                if lease_now <= 0 or time.monotonic_ns() >= lease_now:
                    self._refuse(group, sub.leader_locked(group),
                                 "lease-lapsed")
            sm = sub._sms.get(group)
            if sm is None:
                sm = SQLiteStateMachine(":memory:", resume=True)
                sub._sms[group] = sm
        with self._mu:
            self.hits[mode] += 1
        return sm.query(query)       # sm has its own lock; SQL errors
        #                              surface as the planes' 400 class

    def watermark(self, group: int = 0) -> int:
        with self.sub._cond:
            return self.sub.applied_locked(group)

    # -- the write/admin surface: refuse toward the write tier -----------

    # raftlint: fail-closed
    def _admit_write(self) -> None:
        """Bounded budget on the write-fallback path: each refusal
        still takes the fold lock for the leader hint, so a stampede
        of misdirected writes is shed with a typed Overloaded (the
        planes answer 429 + Retry-After) once `write_cap` lookups are
        already in flight, rather than queueing without bound."""
        with self._mu:
            if self.write_cap > 0 and self._write_inflight >= self.write_cap:
                self.refusals["overloaded"] = \
                    self.refusals.get("overloaded", 0) + 1
                self._overloaded += 1
                raise Overloaded(
                    "replica",
                    min(0.05 * (1 + self._write_inflight), 5.0),
                    "write-fallback budget exhausted")
            self._write_inflight += 1
            return None

    def _leader_hint(self, group: int) -> int:
        # Admission precedes the try: on refusal nothing was admitted,
        # so only a successful admit reaches the decrement.
        self._admit_write()
        try:
            with self.sub._cond:
                return self.sub.leader_locked(group)
        finally:
            with self._mu:
                self._write_inflight -= 1

    def propose(self, query: str, group: int = 0,
                token: Optional[int] = None,
                deadline_ms: Optional[float] = None):
        leader = self._leader_hint(group)
        self._refuse(group, leader, "read-only-tier")

    def abandon(self, query: str, group: int, fut) -> None:
        pass                         # nothing in flight, ever

    def member_change(self, group: int, *a, **k):
        leader = self._leader_hint(group)
        self._refuse(group, leader, "read-only-tier")

    def transfer(self, group: int, *a, **k):
        leader = self._leader_hint(group)
        self._refuse(group, leader, "read-only-tier")

    # -- observability ---------------------------------------------------

    def health_doc(self) -> dict:
        sub = self.sub
        with sub._cond:
            tbl = sub._tbl
            rows = tbl["rows"] if tbl is not None else []
            n = sub.num_groups or len(rows)
            groups = {}
            for g in range(n):
                commit = rows[g][1] if g < len(rows) else 0
                leader = rows[g][4] if g < len(rows) else 0
                applied = sub.applied_locked(g)
                groups[str(g)] = {"role": "replica",
                                  "leader": int(leader),
                                  "applied": int(applied),
                                  "lag": int(max(0, commit - applied))}
            hb = sub.heartbeat_age_ns_locked()
            doc = {"id": 0, "ready": sub.connected, "groups": groups,
                   "replica": {
                       "upstream": f"{sub.upstream[0]}:{sub.upstream[1]}",
                       "epoch": int(sub.epoch),
                       "keymap_epoch": int(sub.keymap_epoch),
                       "connected": bool(sub.connected),
                       "connects": int(sub.connects),
                       "applied": {str(g): int(sub.applied_locked(g))
                                   for g in range(n)},
                       "lag": {g: r["lag"] for g, r in groups.items()},
                       "bytes_rx": int(sub.bytes_rx),
                       "recs_rx": int(sub.recs_rx),
                       "bases_rx": int(sub.bases_rx),
                       "resyncs": int(sub.resyncs),
                       "corrupt_frames": int(sub.corrupt_frames),
                       "heartbeat_age_ms": round(min(hb, 1 << 53) / 1e6,
                                                 3),
                   }}
        if self.unsafe_serve:
            doc["replica"]["unsafe_serve"] = True
        return doc

    def metrics(self) -> dict:
        sub = self.sub
        with self._mu:
            hits = dict(self.hits)
            refusals = dict(self.refusals)
        with sub._cond:
            hb = sub.heartbeat_age_ns_locked()
            m = {
                # The same six-key section the engine exports, so one
                # dashboard reads both tiers (scripts/check_prom.py
                # requires the engine-side series).
                "replica": {
                    "subscribers": 0,
                    "deltas_tx": 0,
                    "bases_tx": 0,
                    "resyncs": int(sub.resyncs),
                    "refusals": sum(refusals.values()),
                    "lag_ms": round(min(hb, 1 << 53) / 1e6, 3),
                },
                "replica_reads": hits,
                "replica_refusals": refusals,
                "replica_stream": {
                    "bytes_rx": int(sub.bytes_rx),
                    "recs_rx": int(sub.recs_rx),
                    "bases_rx": int(sub.bases_rx),
                    "corrupt_frames": int(sub.corrupt_frames),
                    "connects": int(sub.connects),
                },
            }
        # Same overload section the engine exports (zeros-by-default);
        # only the write-fallback budget is live on this tier.
        ov = zero_metrics_doc()
        with self._mu:
            ov["rejected"] = int(self._overloaded)
            ov["total_cap"] = int(self.write_cap)
        m["overload"] = ov
        return m

    def members(self) -> dict:
        return {"replica": True, "upstream":
                f"{self.sub.upstream[0]}:{self.sub.upstream[1]}"}

    def trace_doc(self) -> dict:
        return {"traceEvents": []}

    def events_doc(self, last: int = 256) -> dict:
        return {"events": [], "spans": {}}

    def render_health(self) -> str:
        return json.dumps(self.health_doc(), sort_keys=True) + "\n"

    def render_metrics(self) -> str:
        return json.dumps(self.metrics(), sort_keys=True) + "\n"

    def render_metrics_prom(self) -> str:
        from raftsql_tpu.utils.metrics import prom_render
        return prom_render(self.metrics())

    def render_members(self) -> str:
        return json.dumps(self.members(), sort_keys=True) + "\n"

    def render_trace(self) -> str:
        return json.dumps(self.trace_doc(), sort_keys=True) + "\n"

    def render_events(self) -> str:
        return json.dumps(self.events_doc(), sort_keys=True) + "\n"

    def close(self) -> Optional[Exception]:
        with self._mu:
            if self._closed:
                return None
            self._closed = True
        self.sub.stop()
        with self.sub._cond:
            for sm in self.sub._sms.values():
                try:
                    sm.close()
                except Exception:    # noqa: BLE001
                    pass
        return None
