"""Read-replica tier: the shm delta stream as a replicated wire protocol.

PR 12's shared-memory snapshot plane (runtime/shm.py) made reads free on
the engine's OWN machine: workers map the per-group delta/base log and
serve local/session/follower/lease-linear GETs without a ring round
trip.  This package promotes that exact log into a length-framed,
CRC-checked TCP stream so the same read ladder runs CONTINENTS away —
CD-Raft's placement story (PAPERS.md): lease-holding read-serving peers
near the readers, zero consensus traffic on the read path.

Three pieces:

  * `stream` — the wire protocol: frames reuse transport/codec.py's
    framing discipline (length + whole-frame CRC32, bounds-validated
    before any decode; corruption surfaces as StreamCorruptError to
    DROP, never as an out-of-bounds read) and the record kinds are
    runtime/shm.py's own KIND_DELTA / KIND_BASE, unchanged.
  * `publisher` — engine side: `ReplicaStreamServer` rides the
    `ShmSnapshotPublisher` tee (every applied run, base image and
    keymap flip is mirrored to subscribers the instant it lands in the
    mmap) and bootstraps new subscribers by replaying the publisher's
    append-only log — or, when the log overflowed or a subscriber's
    queue fell behind, by shipping fresh KIND_BASE images (a RESYNC).
    Wired up by `--replica-listen PORT` on server/main.py.
  * `node` — replica side: `ReplicaSubscriber` folds the stream into
    per-group in-memory SQLite replicas exactly as ShmSnapshotReader
    folds the mmap, and `ReplicaDB` fronts it with the RaftDB facade
    both HTTP planes (api/http.py, api/aio.py) already speak — so a
    replica process serves the identical GET surface, and every
    refusal of the fail-closed ladder (stale epoch, uncovered session
    watermark, lapsed/unpublished lease, stream gap/overflow, stale
    heartbeat) degrades to 421 + X-Raft-Leader pointing at the
    authoritative tier, never a stale answer.

Run a replica:  python -m raftsql_tpu.replica --upstream host:port \
                    --port 9221
"""
from raftsql_tpu.replica.node import ReplicaDB, ReplicaSubscriber
from raftsql_tpu.replica.publisher import (ReplicaStreamServer,
                                           attach_replica_plane)
from raftsql_tpu.replica.stream import StreamCorruptError

__all__ = ["ReplicaDB", "ReplicaSubscriber", "ReplicaStreamServer",
           "attach_replica_plane", "StreamCorruptError"]
