"""Wire protocol for the read-replica stream.

One TCP connection per subscriber.  Every frame on the wire is

    u32 payload_len | u32 crc32(payload) | payload

— the same length-plus-whole-frame-CRC framing discipline as
transport/codec.py's batch codec: every declared length is bounds-
checked before a single byte is sliced, and a CRC mismatch surfaces as
the typed `StreamCorruptError` so the receiver can DROP the connection
and resubscribe (corruption is a fault to survive, never a crash and
never a silently-wrong row).  `payload` starts with a one-byte frame
kind:

    K_HELLO  server -> replica   JSON: plane identity — epoch,
                                 keymap_epoch, num_groups.  Sent once,
                                 first.  An epoch different from the
                                 one a resuming replica folded under
                                 means a NEW engine incarnation: the
                                 replica discards its state and refolds
                                 from scratch (exactly the shm reader's
                                 "stale epoch => plane dead" rule, with
                                 re-attach instead of death because the
                                 stream can re-image us).
    K_SUB    replica -> server   JSON: advertised endpoint + the
                                 high-water {group: applied} resume
                                 vector.  The publisher replays its
                                 append-only log from the vector, or
                                 ships fresh KIND_BASE images when the
                                 log can no longer cover it (RESYNC).
    K_REC    server -> replica   One log record: kind/group/index
                                 header + payload — runtime/shm.py's
                                 KIND_DELTA (SQL batch) / KIND_BASE
                                 (SQLite image) records verbatim.
    K_TABLE  server -> replica   The shm header row table as a
                                 heartbeat: per-group applied / commit
                                 / base_index / lease / leader.  The
                                 lease ships as *remaining* nanoseconds
                                 (deadline minus the engine's monotonic
                                 now): CLOCK_MONOTONIC bases don't
                                 transfer across hosts, and stamping
                                 the remainder against the replica's
                                 own clock on arrival makes the local
                                 deadline conservatively EARLY by the
                                 one-way latency — safe side.
    K_ACK    replica -> server   JSON: the replica's folded {group:
                                 applied} vector, for /healthz lag.
"""
from __future__ import annotations

import json
import struct
import zlib
from typing import Dict, List, Tuple

# Frame kinds.
K_HELLO = 1
K_SUB = 2
K_REC = 3
K_TABLE = 4
K_ACK = 5

# Largest payload a peer will accept: a KIND_BASE record is a whole
# serialized SQLite image, so the bound tracks the shm plane's default
# capacity (32 MiB) with headroom rather than a "reasonable message"
# bound.  Anything larger is treated as corruption.
MAX_FRAME = 96 << 20

_FRAME = struct.Struct("<II")          # payload_len, crc32(payload)
_REC_HDR = struct.Struct("<BIQ")       # kind, group, index
_TBL_HDR = struct.Struct("<QQBI")      # epoch, keymap_epoch, flags, num_groups
_TBL_ROW = struct.Struct("<QQQQI")     # applied, commit, base_index,
                                       # lease_remaining_ns, leader(1-based)

TBL_FLAG_LOG_FULL = 1


class StreamCorruptError(ValueError):
    """A frame failed its CRC or declared an impossible length.

    The connection is poisoned (framing can't be trusted past the first
    bad byte): the receiver drops it, counts the corruption, and
    resubscribes with its resume vector.  Never an out-of-bounds read,
    never a wrong row.
    """


class StreamClosed(ConnectionError):
    """Orderly or mid-frame EOF from the peer."""


def encode_frame(kind: int, body: bytes) -> bytes:
    payload = bytes([kind]) + body
    return _FRAME.pack(len(payload), zlib.crc32(payload)) + payload


def read_exact(sock, n: int) -> bytes:
    """Read exactly n bytes from a socket; StreamClosed on EOF."""
    chunks: List[bytes] = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            raise StreamClosed(f"eof after {got}/{n} bytes")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def read_frame(sock) -> Tuple[int, bytes]:
    """Read one frame; returns (kind, body).

    Raises StreamClosed on EOF at a frame boundary or mid-frame, and
    StreamCorruptError on a CRC mismatch or an impossible length.
    """
    hdr = read_exact(sock, _FRAME.size)
    length, crc = _FRAME.unpack(hdr)
    if length < 1 or length > MAX_FRAME:
        raise StreamCorruptError(f"frame length {length} out of bounds")
    payload = read_exact(sock, length)
    if zlib.crc32(payload) != crc:
        raise StreamCorruptError("frame crc mismatch")
    return payload[0], payload[1:]


# --- HELLO ----------------------------------------------------------------

def encode_hello(epoch: int, keymap_epoch: int, num_groups: int) -> bytes:
    body = json.dumps({"epoch": epoch, "keymap_epoch": keymap_epoch,
                       "groups": num_groups}).encode()
    return encode_frame(K_HELLO, body)


def decode_hello(body: bytes) -> Dict[str, int]:
    doc = json.loads(body.decode())
    return {"epoch": int(doc["epoch"]),
            "keymap_epoch": int(doc["keymap_epoch"]),
            "groups": int(doc["groups"])}


# --- SUBSCRIBE / ACK ------------------------------------------------------

def encode_subscribe(endpoint: str, applied: Dict[int, int]) -> bytes:
    body = json.dumps({"endpoint": endpoint,
                       "applied": {str(g): int(n)
                                   for g, n in applied.items()}}).encode()
    return encode_frame(K_SUB, body)


def decode_subscribe(body: bytes) -> Tuple[str, Dict[int, int]]:
    doc = json.loads(body.decode())
    applied = {int(g): int(n)
               for g, n in dict(doc.get("applied", {})).items()}
    return str(doc.get("endpoint", "")), applied


def encode_ack(applied: Dict[int, int]) -> bytes:
    body = json.dumps({"applied": {str(g): int(n)
                                   for g, n in applied.items()}}).encode()
    return encode_frame(K_ACK, body)


def decode_ack(body: bytes) -> Dict[int, int]:
    doc = json.loads(body.decode())
    return {int(g): int(n)
            for g, n in dict(doc.get("applied", {})).items()}


# --- REC ------------------------------------------------------------------

def encode_rec(kind: int, group: int, index: int, payload: bytes) -> bytes:
    return encode_frame(K_REC, _REC_HDR.pack(kind, group, index) + payload)


def decode_rec(body: bytes) -> Tuple[int, int, int, bytes]:
    if len(body) < _REC_HDR.size:
        raise StreamCorruptError("short REC header")
    kind, group, index = _REC_HDR.unpack_from(body, 0)
    return kind, group, index, body[_REC_HDR.size:]


# --- TABLE ----------------------------------------------------------------

def encode_table(epoch: int, keymap_epoch: int, log_full: bool,
                 rows: List[Tuple[int, int, int, int, int]]) -> bytes:
    """rows: per group (applied, commit, base_index, lease_remaining_ns,
    leader 1-based / 0 unknown)."""
    flags = TBL_FLAG_LOG_FULL if log_full else 0
    body = bytearray(_TBL_HDR.pack(epoch, keymap_epoch, flags, len(rows)))
    for row in rows:
        body += _TBL_ROW.pack(*row)
    return encode_frame(K_TABLE, bytes(body))


def decode_table(body: bytes):
    """Returns (epoch, keymap_epoch, log_full, rows)."""
    if len(body) < _TBL_HDR.size:
        raise StreamCorruptError("short TABLE header")
    epoch, keymap_epoch, flags, n = _TBL_HDR.unpack_from(body, 0)
    need = _TBL_HDR.size + n * _TBL_ROW.size
    if n > 1 << 20 or len(body) < need:
        raise StreamCorruptError("TABLE row count out of bounds")
    rows = [_TBL_ROW.unpack_from(body, _TBL_HDR.size + i * _TBL_ROW.size)
            for i in range(n)]
    return epoch, keymap_epoch, bool(flags & TBL_FLAG_LOG_FULL), rows


def parse_hostport(spec: str, default_port: int = 9220) -> Tuple[str, int]:
    """'host:port' / 'host' -> (host, port); tolerant of bare ports."""
    spec = spec.strip()
    if ":" in spec:
        host, _, port = spec.rpartition(":")
        return host or "127.0.0.1", int(port)
    if spec.isdigit():
        return "127.0.0.1", int(spec)
    return spec, default_port
