"""Engine side of the read-replica tier: the stream server.

`ReplicaStreamServer` rides the `ShmSnapshotPublisher` tee
(runtime/shm.py): every applied delta run, snapshot base and keymap
flip is mirrored — under the publisher lock, the instant it lands —
into per-subscriber bounded queues, and per-subscriber sender threads
frame them onto TCP (replica/stream.py) interleaved with TABLE
heartbeats carrying the watermark/lease/leader columns.

Bootstrap and resume share one invariant with the shm reader: a
subscriber must never see a delta stream whose prefix it is missing.
Registration runs INSIDE the publisher lock (`stream_register`), so
the returned log head and the first queued tee event are adjacent —
the server replays the publisher's append-only mmap log up to that
head (filtered by the subscriber's resume vector), then drains the
queue.  When the log can no longer cover a subscriber — the mmap
overflowed (`log_full`), or the subscriber's queue lapped — the server
RESYNCs: it discards the queue backlog and ships fresh `KIND_BASE`
images serialized from the live state machines, which the replica's
resume-mode fold makes idempotent.  Overflow therefore kills only the
local worker fast path, never the stream.

`attach_replica_plane(rdb, port)` is the `--replica-listen` wiring for
server/main.py: it reuses the RingServer's publisher when `--workers`
already attached one, else creates a publisher of its own (plus the
2ms refresh thread the lease columns need), then starts the server and
hangs it off `rdb.replica_plane` so /healthz and /metrics export the
tier.
"""
from __future__ import annotations

import logging
import queue
import socket
import tempfile
import threading
import time
from typing import Dict, List, Optional

from raftsql_tpu.replica import stream as wire
from raftsql_tpu.runtime.shm import KIND_BASE, KIND_DELTA

log = logging.getLogger("raftsql.replica")

# Tee events a subscriber may fall behind by before the server stops
# replaying its queue and re-images it from fresh bases instead.
QUEUE_DEPTH = 4096
TABLE_INTERVAL_S = 0.005


def _sever(conn: socket.socket) -> None:
    """shutdown(SHUT_RDWR) BEFORE close: close() alone neither wakes a
    sibling thread parked in recv() on the same socket nor sends the
    FIN while that syscall pins the file description — the peer would
    hang on a connection that is already dead on this side."""
    try:
        conn.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        conn.close()
    except OSError:
        pass


class _Subscriber:
    """One connected replica: its socket, bounded tee queue and the
    acked applied vector it reports back for /healthz lag."""

    def __init__(self, conn: socket.socket, endpoint: str,
                 resume: Dict[int, int]):
        self.conn = conn
        self.endpoint = endpoint
        self.resume = resume
        self.q: "queue.Queue" = queue.Queue(maxsize=QUEUE_DEPTH)
        self.needs_resync = False    # queue lapped: re-image, don't replay
        self.alive = True
        self.acked: Dict[int, int] = dict(resume)
        self.last_ack_ns = time.monotonic_ns()
        self._wmu = threading.Lock()  # raftlint: guarded-by=_wmu (sendall)

    def send(self, frame: bytes) -> None:
        with self._wmu:
            self.conn.sendall(frame)


class ReplicaStreamServer:
    """Accepts replica subscriptions and streams the publisher's
    delta/base log at them.  One accept thread; per subscriber, one
    sender thread (queue drain + TABLE heartbeat) and one reader
    thread (ACK vectors)."""

    def __init__(self, pub, port: int, host: str = ""):
        self.pub = pub
        self.host = host
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(32)
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._mu = threading.Lock()
        self._subs: List[_Subscriber] = []   # raftlint: guarded-by=_mu
        self._threads: List[threading.Thread] = []
        # Stream counters (ISSUE 19 satellite: /metrics `replica.*`).
        self.deltas_tx = 0
        self.bases_tx = 0
        self.bytes_tx = 0
        self.resyncs = 0
        pub.tee = self._tee
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="replica-accept")

    def start(self) -> None:
        self._accept_thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self.pub.tee is self._tee:
            self.pub.tee = None
        try:
            self._sock.close()
        except OSError:
            pass
        with self._mu:
            subs = list(self._subs)
        for sub in subs:
            sub.alive = False
            _sever(sub.conn)
        for t in list(self._threads):
            t.join(timeout=5)

    # -- tee (called on the APPLY thread, under the publisher lock) ------

    def _tee(self, *event) -> None:
        """Non-blocking fan-out of one publish event.  A full queue
        marks the subscriber for RESYNC instead of blocking: the apply
        thread must never wait on a slow replica."""
        with self._mu:
            subs = list(self._subs)
        for sub in subs:
            if not sub.alive:
                continue
            try:
                sub.q.put_nowait(event)
            except queue.Full:
                sub.needs_resync = True

    # -- per-connection plumbing ----------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return                       # socket closed: shutting down
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True, name="replica-conn")
            with self._mu:
                self._threads.append(t)
            t.start()

    def _serve_conn(self, conn: socket.socket) -> None:
        sub: Optional[_Subscriber] = None
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            epoch, keymap_epoch, _full, _rows = self.pub.table_snapshot()
            conn.sendall(wire.encode_hello(epoch, keymap_epoch,
                                           self.pub.num_groups))
            kind, body = wire.read_frame(conn)
            if kind != wire.K_SUB:
                return
            endpoint, resume = wire.decode_subscribe(body)
            sub = _Subscriber(conn, endpoint, resume)
            # Register INSIDE the publisher lock: `head` and the first
            # queued tee event are adjacent — replaying [0, head) then
            # draining the queue reconstructs the full stream.
            head, full = self.pub.stream_register(
                lambda: self._register(sub))
            if full:
                # The mmap log can't cover bootstrap: re-image from
                # fresh serializations instead (counted as a resync —
                # it is one, just at subscribe time).
                self._send_fresh_bases(sub)
                with self._mu:
                    self.resyncs += 1
            else:
                self._replay_log(sub, head)
            reader = threading.Thread(target=self._read_loop, args=(sub,),
                                      daemon=True, name="replica-acks")
            with self._mu:
                self._threads.append(reader)
            reader.start()
            self._send_loop(sub)
        except (wire.StreamClosed, wire.StreamCorruptError, OSError,
                ValueError):
            pass                             # subscriber gone / garbage
        finally:
            if sub is not None:
                sub.alive = False
                with self._mu:
                    if sub in self._subs:
                        self._subs.remove(sub)
            _sever(conn)

    def _register(self, sub: _Subscriber) -> None:
        with self._mu:
            self._subs.append(sub)

    def _replay_log(self, sub: _Subscriber, head: int) -> None:
        """Bootstrap from the publisher's append-only log, skipping
        records the subscriber's resume vector already covers."""
        for kind, group, index, payload in \
                self.pub.read_log_records(0, head):
            if index <= sub.resume.get(group, 0):
                continue                     # replica already folded it
            self._send_rec(sub, kind, group, index, payload)

    def _send_fresh_bases(self, sub: _Subscriber) -> None:
        """RESYNC: ship a fresh image of every group that has state.
        Serialized OUTSIDE the publisher lock (state machines have
        their own); any tee events queued meanwhile land after these
        bases and dedup against them on the replica."""
        for g in range(self.pub.num_groups):
            got = self.pub.fresh_base(g)
            if got is None:
                continue
            idx, blob = got
            self._send_rec(sub, KIND_BASE, g, idx, blob)

    def _send_rec(self, sub: _Subscriber, kind: int, group: int,
                  index: int, payload: bytes) -> None:
        frame = wire.encode_rec(kind, group, index, payload)
        sub.send(frame)
        with self._mu:
            self.bytes_tx += len(frame)
            if kind == KIND_BASE:
                self.bases_tx += 1
            else:
                self.deltas_tx += 1

    def _send_table(self, sub: _Subscriber) -> None:
        epoch, keymap_epoch, full, rows = self.pub.table_snapshot()
        now = time.monotonic_ns()
        out = []
        for applied, commit, base, lease_ns, leader in rows:
            # Lease ships as REMAINING ns against the engine's clock:
            # monotonic bases don't transfer across hosts, and stamping
            # the remainder on arrival leaves the replica's deadline
            # conservatively EARLY by the one-way latency.
            remaining = lease_ns - now if lease_ns > now else 0
            out.append((applied, commit, base, remaining, leader))
        frame = wire.encode_table(epoch, keymap_epoch, full, out)
        sub.send(frame)
        with self._mu:
            self.bytes_tx += len(frame)

    def _send_loop(self, sub: _Subscriber) -> None:
        last_table = 0.0
        while sub.alive and not self._stop.is_set():
            try:
                event = sub.q.get(timeout=TABLE_INTERVAL_S / 2)
            except queue.Empty:
                event = None
            if sub.needs_resync:
                # Drop the lapped backlog, re-image.  Events teed
                # after this drain apply above the fresh bases.
                while True:
                    try:
                        sub.q.get_nowait()
                    except queue.Empty:
                        break
                sub.needs_resync = False
                self._send_fresh_bases(sub)
                with self._mu:
                    self.resyncs += 1
                event = None
            if event is not None:
                self._send_event(sub, event)
            now = time.monotonic()
            if now - last_table >= TABLE_INTERVAL_S:
                self._send_table(sub)
                last_table = now

    def _send_event(self, sub: _Subscriber, event) -> None:
        if event[0] == "deltas":
            for group, items in event[1].items():
                for sql, index in items:
                    self._send_rec(sub, KIND_DELTA, group, index,
                                   sql.encode("utf-8"))
        elif event[0] == "base":
            _, group, index, blob = event
            self._send_rec(sub, KIND_BASE, group, index, blob)
        elif event[0] == "keymap":
            self._send_table(sub)    # next snapshot carries the epoch

    def _read_loop(self, sub: _Subscriber) -> None:
        """Consume ACK frames: the replica's folded applied vector,
        exported as per-subscriber lag on the engine's /healthz."""
        try:
            while sub.alive and not self._stop.is_set():
                kind, body = wire.read_frame(sub.conn)
                if kind == wire.K_ACK:
                    sub.acked.update(wire.decode_ack(body))
                    sub.last_ack_ns = time.monotonic_ns()
        except (wire.StreamClosed, wire.StreamCorruptError, OSError,
                ValueError):
            sub.alive = False

    # -- observability ---------------------------------------------------

    def metrics_doc(self) -> dict:
        """The engine's `replica` /metrics section — the same six keys
        a detached engine zero-fills (runtime/db.py metrics), plus the
        byte counter.  `refusals` is 0 by construction here: refusing
        is the REPLICA's half of the ladder, reported on its own
        /metrics; `lag_ms` is the oldest subscriber's silence since
        its last ACK (0 with no subscribers)."""
        now = time.monotonic_ns()
        with self._mu:
            lag_ms = max((now - s.last_ack_ns for s in self._subs
                          if s.alive), default=0) / 1e6
            return {"subscribers": len(self._subs),
                    "deltas_tx": self.deltas_tx,
                    "bases_tx": self.bases_tx,
                    "resyncs": self.resyncs,
                    "refusals": 0,
                    "lag_ms": round(lag_ms, 3),
                    "bytes_tx": self.bytes_tx}

    def health_doc(self) -> dict:
        """The engine-side `replica` /healthz section: advertised
        subscriber endpoints (the client sweep adopts these) and
        per-subscriber applied/lag."""
        _epoch, _km, _full, rows = self.pub.table_snapshot()
        with self._mu:
            subs = list(self._subs)
            doc = {"listen": self.port,
                   "subscribers": len(subs),
                   "deltas_tx": self.deltas_tx,
                   "bases_tx": self.bases_tx,
                   "resyncs": self.resyncs,
                   "bytes_tx": self.bytes_tx,
                   "endpoints": [s.endpoint for s in subs if s.endpoint]}
        tails = []
        for s in subs:
            lag = {g: max(0, rows[g][0] - s.acked.get(g, 0))
                   for g in range(len(rows))}
            tails.append({"endpoint": s.endpoint,
                          "acked": {str(g): int(n)
                                    for g, n in sorted(s.acked.items())},
                          "lag": {str(g): int(n)
                                  for g, n in sorted(lag.items())}})
        doc["tails"] = tails
        return doc


def attach_replica_plane(rdb, port: int, host: str = ""):
    """Wire `--replica-listen PORT` onto a built RaftDB: reuse the
    RingServer's shm publisher when one is attached (--workers), else
    create a dedicated one (with its own 2ms lease-refresh thread) —
    then start the stream server and export it at rdb.replica_plane."""
    pub = getattr(rdb, "shm", None)
    owned_dir = None
    refresh_stop = None
    if pub is None:
        from raftsql_tpu.runtime.shm import ShmSnapshotPublisher
        owned_dir = tempfile.mkdtemp(prefix="raftsql-replica-")
        pub = ShmSnapshotPublisher(owned_dir, rdb.num_groups)
        # Attach-then-start ordering (runtime/ring.py precedent): the
        # apply thread buffers deltas from the attach instant, start()
        # opens the log with base images below them.
        rdb.shm = pub
        pub.start(rdb._snapshot_of, rdb.watermark)
        node = getattr(getattr(rdb, "pipe", None), "node", None)
        commit_of = getattr(node, "commit_watermark", lambda g: 0)
        leader_of = getattr(node, "leader_of", lambda g: -1)
        lease_of = getattr(node, "lease_deadline_s", lambda g: 0.0)
        refresh_stop = threading.Event()

        def _refresh() -> None:
            while not refresh_stop.is_set():
                try:
                    pub.refresh(commit_of, leader_of, lease_of)
                except Exception:            # noqa: BLE001
                    log.exception("replica shm refresh failed; stopping")
                    return
                refresh_stop.wait(0.002)

        threading.Thread(target=_refresh, daemon=True,
                         name="replica-shm-refresh").start()
    srv = ReplicaStreamServer(pub, port, host)
    srv.start()

    base_stop = srv.stop

    def _stop() -> None:
        base_stop()
        if refresh_stop is not None:
            refresh_stop.set()
            if getattr(rdb, "shm", None) is pub:
                rdb.shm = None
            pub.close()
        if owned_dir is not None:
            import shutil
            shutil.rmtree(owned_dir, ignore_errors=True)

    srv.stop = _stop                         # type: ignore[method-assign]
    rdb.replica_plane = srv
    return srv
