"""Multi-chip SPMD consensus: the cluster step sharded over a device mesh.

The reference scales by running each raft peer as its own OS process and
wiring them with HTTP streams (reference raft.go:248-266, Procfile:2-4).
The TPU-native design instead lays the whole multi-raft state onto a 2-D
`jax.sharding.Mesh`:

  * ``groups`` axis — the data-parallel analog.  Raft groups are
    embarrassingly parallel: each group's consensus math touches only its
    own rows, so sharding the ``G`` axis needs **zero** collectives.
  * ``peers`` axis — the model-parallel analog.  When one group's peers
    live on different chips, the per-tick message exchange (the reference's
    rafthttp `transport.Send`, raft.go:230) becomes a single
    ``jax.lax.all_to_all`` over ICI: the outbox's src→dst transpose, which
    is a pure data-layout change on one chip (core/cluster.py `deliver`),
    turns into the collective form of the same permutation.

This is BASELINE.json config 5 ("groups sharded over v5e-8, peer-vote
allreduce over ICI") — note the vote/match *reduction* itself stays inside
`peer_step` as dense math over the message-slot axis; what rides ICI is the
message exchange that feeds it.

Everything is built with `shard_map` so the per-device program is exactly
the single-chip `peer_step` vmapped over the local peer rows: one compiled
program, no per-group Python, collectives inserted only where the mesh
demands them.
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# jax.shard_map graduated from jax.experimental in newer releases; this
# container pins an older jax, so resolve whichever spelling exists.
try:
    _shard_map = jax.shard_map
except AttributeError:                              # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

from raftsql_tpu.config import RaftConfig
from raftsql_tpu.core.state import I32, Inbox, PeerState, StepInfo
from raftsql_tpu.core.step import peer_step

PEERS_AXIS = "peers"
GROUPS_AXIS = "groups"


def make_mesh(n_peer_shards: int, n_group_shards: int,
              devices=None) -> Mesh:
    """Build the ('peers', 'groups') mesh over the first pp*gg devices."""
    import numpy as np

    devices = jax.devices() if devices is None else devices
    need = n_peer_shards * n_group_shards
    if len(devices) < need:
        raise ValueError(
            f"mesh {n_peer_shards}x{n_group_shards} needs {need} devices, "
            f"have {len(devices)}")
    grid = np.asarray(devices[:need]).reshape(n_peer_shards, n_group_shards)
    return Mesh(grid, (PEERS_AXIS, GROUPS_AXIS))


def _spec2() -> P:
    return P(PEERS_AXIS, GROUPS_AXIS)


def state_specs() -> PeerState:
    """PartitionSpec tree for a stacked PeerState (leaves [P, G, ...]).

    The trailing peer axis of votes/match/next_idx is the *message-slot*
    axis (all P peers of a group, as seen by one peer) — it is replicated,
    only the leading owner-peer axis is sharded.
    """
    s2, s3 = _spec2(), P(PEERS_AXIS, GROUPS_AXIS, None)
    return PeerState(
        term=s2, voted_for=s2, role=s2, leader_hint=s2,
        commit=s2, log_len=s2, log_term=s3,
        tbl_pos=s3, tbl_term=s3,
        elapsed=s2, timeout=s2, hb_elapsed=s2,
        votes=s3, match=s3, next_idx=s3,
        voters=s3, voters_joint=s3,
        resp_tick=s3, xfer_target=s2,
        rng=P(PEERS_AXIS), tick=P(PEERS_AXIS))


def inbox_specs() -> Inbox:
    s3 = P(PEERS_AXIS, GROUPS_AXIS, None)
    s4 = P(PEERS_AXIS, GROUPS_AXIS, None, None)
    return Inbox(
        v_type=s3, v_term=s3, v_last_idx=s3, v_last_term=s3, v_granted=s3,
        a_type=s3, a_term=s3, a_prev_idx=s3, a_prev_term=s3, a_n=s3,
        a_ents=s4, a_commit=s3, a_success=s3, a_match=s3)


def info_specs() -> StepInfo:
    s2 = _spec2()
    return StepInfo(
        commit=s2, role=s2, term=s2, voted_for=s2, leader_hint=s2,
        prop_base=s2, prop_accepted=s2, noop=s2,
        app_from=s2, app_start=s2, app_n=s2, app_conflict=s2,
        new_log_len=s2, lease=s2, xfer=s2,
        next_idx=P(PEERS_AXIS, GROUPS_AXIS, None),
        floor=s2, timer_margin=P(PEERS_AXIS))


def shard_cluster_arrays(mesh: Mesh, states: PeerState, inboxes: Inbox,
                         prop_n: jax.Array | None = None):
    """Place host-built stacked arrays onto the mesh with the right layout."""
    put = lambda tree, specs: jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), tree, specs)
    out = [put(states, state_specs()), put(inboxes, inbox_specs())]
    if prop_n is not None:
        out.append(jax.device_put(prop_n, NamedSharding(mesh, _spec2())))
    return tuple(out)


def _route(outbox_leaf: jax.Array, n_peer_shards: int) -> jax.Array:
    """src→dst message exchange for one outbox leaf, local block view.

    Local shape [p_loc(src), G_loc, P(dst global), ...].  The swapaxes is
    the on-chip half of the permutation; the tiled all_to_all moves each
    destination block to its owner shard over ICI, yielding
    [p_loc(dst local), G_loc, P(src global), ...] — exactly the Inbox
    layout `peer_step` consumes.  With an unsharded peer axis this
    degenerates to core/cluster.py's `deliver` transpose.
    """
    x = jnp.swapaxes(outbox_leaf, 0, 2)
    if n_peer_shards > 1:
        x = jax.lax.all_to_all(x, PEERS_AXIS, split_axis=0, concat_axis=2,
                               tiled=True)
    return x


def make_sharded_step_fn(cfg: RaftConfig, mesh: Mesh):
    """The local-block step body (for composition inside shard_map).

    Validates divisibility, derives the per-shard config, and returns a
    function over LOCAL blocks: states [p_loc, G_loc, ...], inboxes
    [p_loc, G_loc, P, ...], prop_n [p_loc, G_loc], timer_inc [p_loc]
    (this peer block's slice of the global [P] per-peer timer advance —
    the same skew seam core/cluster.py cluster_step exposes, so chaos
    SkewWindow schedules and per-peer pacing express identically on the
    mesh).
    """
    pp = mesh.shape[PEERS_AXIS]
    gg = mesh.shape[GROUPS_AXIS]
    if cfg.num_peers % pp:
        raise ValueError(f"num_peers {cfg.num_peers} not divisible by "
                         f"peer shards {pp}")
    if cfg.num_groups % gg:
        raise ValueError(f"num_groups {cfg.num_groups} not divisible by "
                         f"group shards {gg}")
    local_cfg = dataclasses.replace(cfg, num_groups=cfg.num_groups // gg)
    p_loc = cfg.num_peers // pp

    def _step(states: PeerState, inboxes: Inbox, prop_n: jax.Array,
              timer_inc: jax.Array):
        pidx = jax.lax.axis_index(PEERS_AXIS)
        self_ids = (pidx * p_loc + jnp.arange(p_loc, dtype=I32)).astype(I32)
        goff = jax.lax.axis_index(GROUPS_AXIS) * local_cfg.num_groups
        new_states, outboxes, infos = jax.vmap(
            lambda st, ib, pn, sid, ti: peer_step(
                local_cfg, st, ib, pn, sid, goff, timer_inc=ti))(
                    states, inboxes, prop_n, self_ids, timer_inc)
        delivered = jax.tree.map(lambda x: _route(x, pp), outboxes)
        # timer_margin is a per-(peer, group-shard) min; the host wants
        # the per-peer min over ALL groups, so reduce it over the group
        # axis here — that also makes the P(PEERS_AXIS) out_spec's
        # replication-over-groups claim true by construction.
        infos = infos._replace(timer_margin=jax.lax.pmin(
            infos.timer_margin, GROUPS_AXIS))
        return new_states, delivered, infos

    _step.p_loc = p_loc
    return _step


def timer_spec() -> P:
    """PartitionSpec of the [P] per-peer timer advance vector: sharded
    with the owner-peer axis, replicated over groups."""
    return P(PEERS_AXIS)


def make_sharded_cluster_step(cfg: RaftConfig, mesh: Mesh):
    """Compile one whole-cluster LOCKSTEP tick SPMD over `mesh`.

    Returns jitted fn(states, inboxes, prop_n) -> (states, inboxes, infos)
    with every leaf sharded per {state,inbox,info}_specs.  Timers
    advance 1 per peer per tick; the durable mesh runtime uses
    `make_sharded_cluster_step_host`, which takes the per-peer vector.
    """
    step = make_sharded_step_fn(cfg, mesh)

    def _lockstep(states, inboxes, prop_n):
        return step(states, inboxes, prop_n,
                    jnp.ones((step.p_loc,), I32))

    mapped = _shard_map(
        _lockstep, mesh=mesh,
        in_specs=(state_specs(), inbox_specs(), _spec2()),
        out_specs=(state_specs(), inbox_specs(), info_specs()))
    return jax.jit(mapped, donate_argnums=(0, 1))


def make_sharded_cluster_step_host(cfg: RaftConfig, mesh: Mesh):
    """The sharded tick with single-array host info, for the durable
    mesh runtime (runtime/mesh.py MeshClusterNode): same SPMD program
    as `make_sharded_cluster_step`, but StepInfo crosses the host
    boundary as ONE packed [P, G, INFO_NCOLS] i32 array (core/step.py
    pack_info) — the host plane (WAL, payload mirroring, publish)
    consumes identical columns whether the cluster runs fused on one
    chip or sharded over the mesh.

    Returns jitted fn(states, inboxes, prop_n, timer_inc[P]) ->
    (states, inboxes, packed_info, busy).  `timer_inc` is the per-peer
    timer advance (pass ones for lockstep); `busy` is the replicated
    scalar device-activity bit the fused runtime's idle parking keys on
    (core/cluster.py cluster_step_host): vote traffic, entry-carrying
    appends, or rejected append responses anywhere on the mesh."""
    from raftsql_tpu.config import MSG_REQ, MSG_RESP
    from raftsql_tpu.core.step import pack_info

    step = make_sharded_step_fn(cfg, mesh)

    def _step(states, inboxes, prop_n, timer_inc):
        states, ib, infos = step(states, inboxes, prop_n, timer_inc)
        busy = (jnp.any(ib.v_type != 0)
                | jnp.any((ib.a_type == MSG_REQ) & (ib.a_n > 0))
                | jnp.any((ib.a_type == MSG_RESP) & ~ib.a_success))
        # OR across every mesh shard: replicated scalar (out_spec P()).
        busy = jax.lax.pmax(
            jax.lax.pmax(busy.astype(I32), PEERS_AXIS),
            GROUPS_AXIS) > 0
        return states, ib, jax.vmap(pack_info)(infos), busy

    mapped = _shard_map(
        _step, mesh=mesh,
        in_specs=(state_specs(), inbox_specs(), _spec2(), timer_spec()),
        out_specs=(state_specs(), inbox_specs(),
                   P(PEERS_AXIS, GROUPS_AXIS, None), P()))
    return jax.jit(mapped, donate_argnums=(0, 1))


def make_sharded_cluster_run(cfg: RaftConfig, mesh: Mesh, num_ticks: int):
    """Compile a `num_ticks`-tick scan of the sharded step (device-resident).

    Returns jitted fn(states, inboxes, prop_n[T, P, G]) ->
    (states, inboxes, committed_total) where committed_total is a replicated
    scalar: the total number of log entries newly committed across ALL
    groups over the run (per-group max commit over peers, summed over
    groups, psum'd over the mesh) — so the benchmark harness moves exactly
    one scalar over the host boundary per run.
    """
    step = make_sharded_step_fn(cfg, mesh)

    def _run(states, inboxes, prop_n):
        ones = jnp.ones((step.p_loc,), I32)

        def group_commit(commit):   # [p_loc, G_loc] -> replicated-[G_loc]
            return jax.lax.pmax(jnp.max(commit, axis=0), PEERS_AXIS)

        commit0 = group_commit(states.commit)

        def body(carry, prop_t):
            st, ib = carry
            st, ib, _ = step(st, ib, prop_t, ones)
            return (st, ib), None

        (states, inboxes), _ = jax.lax.scan(
            body, (states, inboxes), prop_n, length=num_ticks)
        adv = jnp.sum(group_commit(states.commit) - commit0)
        total = jax.lax.psum(adv, GROUPS_AXIS)
        return states, inboxes, total

    return jax.jit(
        _shard_map(
            _run, mesh=mesh,
            in_specs=(state_specs(), inbox_specs(),
                      P(None, PEERS_AXIS, GROUPS_AXIS)),
            out_specs=(state_specs(), inbox_specs(), P())),
        donate_argnums=(0, 1))
