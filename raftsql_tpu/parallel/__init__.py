"""Multi-chip SPMD execution of the batched consensus core."""

from raftsql_tpu.parallel.sharded import (GROUPS_AXIS, PEERS_AXIS, make_mesh,
                                          make_sharded_cluster_run,
                                          make_sharded_cluster_step,
                                          make_sharded_cluster_step_host,
                                          shard_cluster_arrays, timer_spec)

__all__ = [
    "GROUPS_AXIS", "PEERS_AXIS", "make_mesh", "make_sharded_cluster_run",
    "make_sharded_cluster_step", "make_sharded_cluster_step_host",
    "shard_cluster_arrays", "timer_spec",
]
