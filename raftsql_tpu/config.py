"""Configuration for the TPU multi-raft engine.

The reference (chzchzchz/raftsql) hard-codes its consensus timing and sizing
constants (reference raft.go:154-158, 207; listener.go:56).  Here they are
named fields of a dataclass, plus the batching knobs that only exist in the
TPU-native design ({num_groups, peers, log window, entries per append}).

Reference constant parity:
  - tick_interval_s     <- 100ms ticker           (reference raft.go:207)
  - election_ticks      <- ElectionTick: 10       (reference raft.go:154)
  - heartbeat_ticks     <- HeartbeatTick: 1       (reference raft.go:155)
  - max_entries_per_msg <- MaxSizePerMsg: 1MiB    (reference raft.go:157),
        recast as an entry-count cap per AppendEntries batch
  - log_window          <- MaxInflightMsgs: 256   (reference raft.go:158),
        recast as the on-device log-metadata ring capacity; the host flow
        controller stops admitting proposals when uncommitted entries would
        overrun the ring (the reference's in-flight window analog)
"""
from __future__ import annotations

import dataclasses

# Role codes for the [groups] role array.
FOLLOWER = 0
CANDIDATE = 1
LEADER = 2
PRECANDIDATE = 3

# Message type codes (shared by the vote slot and the append slot).
MSG_NONE = 0
MSG_REQ = 1
MSG_RESP = 2
# Prevote (vote slot only): a timed-out peer probes for election viability
# at term+1 WITHOUT bumping any term (raft §9.6 / etcd PreVote).  Codes
# ride the same u8 wire field as MSG_REQ/MSG_RESP (transport/codec.py).
MSG_PREREQ = 3
MSG_PRERESP = 4
# Leadership transfer (vote slot only): the raft thesis §3.10 TimeoutNow.
# A transferring leader sends it to the caught-up target, which starts a
# REAL election at term+1 immediately — skipping prevote entirely, which
# is what lets the grant bypass the Phase-2b in-lease refusal for exactly
# that target (every other peer still refuses in-lease probes).  Code >= 3
# so Phase 1's term-adoption mask (REQ/RESP/rejected-PRERESP only) never
# bumps terms off a stray TimeoutNow.
MSG_TIMEONOW = 5

# xfer_target sentinel: no leadership transfer pending for the group.
NO_XFER = -1

# Floor-reject resync marker: a follower that cannot verify an append
# below its transition-table floor answers with
# a_match = own_log_len + FLOOR_HINT_BIAS — an EXPLICIT "resync UP to my
# tip" request (core/step.py Phase 4).  The leader strips the bias and
# jumps next_idx to hint + 1 (Phase 5); ordinary conflict hints are
# never biased, so a late in-flight ordinary reject can no longer be
# mistaken for a resync request (which cost extra probe rounds when the
# signal was inferred from hint magnitude).  The bias rides the normal
# i32 match field on both wire forms; log lengths stay far below 2^30
# (the device ring window W bounds uncommitted depth, and positions are
# compacted host-side).
FLOOR_HINT_BIAS = 1 << 30

# voted_for sentinel: no vote cast this term.
NO_VOTE = -1
# leader_hint sentinel: leader unknown.
NO_LEADER = -1

# Default WAL segment rotation threshold (storage/wal.py; also the CLI's
# --wal-segment-bytes default).
WAL_SEGMENT_BYTES_DEFAULT = 4 << 20


@dataclasses.dataclass(frozen=True)
class RaftConfig:
    """Static shape/timing configuration of a batched multi-raft engine.

    All fields are static w.r.t. jit: changing any of them recompiles the
    step function.
    """

    num_groups: int = 1          # G: raft groups advanced per device step
    num_peers: int = 3           # P: replicas per group (reference: 3, Procfile)
    log_window: int = 256        # W: on-device log-metadata ring capacity
    max_entries_per_msg: int = 8  # E: entries per AppendEntries batch

    # Initial voter set (dynamic membership, raftsql_tpu/membership/).
    # None = every peer slot is a voter (the static-cluster default —
    # quorum math then reproduces the fixed-quorum kernels bit for bit).
    # A tuple of 0-based slot ids seeds a smaller voter set: the
    # remaining slots boot as spare/learner capacity that still receives
    # AppendEntries but is masked out of every quorum until a committed
    # conf-change entry promotes it.  P is the provisioned slot CAPACITY
    # (a static device shape); membership changes move voter bits
    # between slots, they never resize P.
    initial_voters: "tuple | None" = None

    # Membership masks may CHANGE at runtime (a conf entry has applied,
    # or could).  While False with initial_voters=None, the step takes
    # the STATIC full-voter fast path: the per-group [G, P] voter masks
    # are known constants, so the mask-weighted quorum kernels collapse
    # back to the fixed-quorum forms (one sort + static gather instead
    # of two masked sorts + one-hot selects, no mask gates on vote
    # grants).  The masked kernels with a full mask are bit-identical
    # (property-tested), so the runtimes flip this lazily — the moment
    # a conf entry is restored/applied/enabled — at the cost of one
    # recompile, and the static-cluster hot path pays nothing for the
    # membership subsystem.
    dynamic_membership: bool = False

    # Timing, in ticks (one device step == one tick).
    election_ticks: int = 10     # min randomized election timeout
    heartbeat_ticks: int = 1     # leader heartbeat period

    # Wall-clock seconds per tick for the host event loop.  The reference
    # ticks at 100ms; the batched engine defaults much faster because one
    # device step advances every group at once.
    tick_interval_s: float = 0.001

    # PreVote (raft §9.6): a timed-out peer first probes a quorum at
    # term+1 without bumping terms; only a successful probe starts a real
    # election.  Keeps a partitioned peer's term from inflating, so its
    # rejoin cannot depose a healthy leader.  The modern etcd/raft (the
    # successor of the engine the reference vendors, raft.go:30) ships
    # this; the 2015 vendored copy predates it.
    prevote: bool = True

    # Leader leases (raft §6.4.1 / thesis §6.3, the read plane): a
    # leader whose latest heartbeat round was confirmed by a quorum at
    # (its local) time s may serve LINEARIZABLE reads without a fresh
    # quorum round while `now + max_clock_skew < s + lease_ticks`.
    # Soundness rests on the prevote lease check (core/step.py Phase
    # 2b): every confirmed peer refuses election probes until
    # `election_ticks` of ITS OWN clock elapse after s, and any new
    # quorum must intersect the confirmed one — so the deployment must
    # guarantee  lease_ticks + max_clock_skew <= election_ticks / rho
    # where rho bounds how much faster any peer's clock can run
    # relative to the lease holder's (the chaos skew machinery
    # deliberately violates this to prove the invariant harness would
    # catch a mis-sized bound).  0 disables leases: linear reads always
    # pay the ReadIndex quorum round.  Requires prevote.
    lease_ticks: int = 0

    # Clock-skew slack subtracted from every lease validity check (in
    # ticks of the lease holder's clock).  Part of the lease bound
    # above; meaningless while lease_ticks == 0.
    max_clock_skew: int = 1

    # Pipelined-replication window: how many optimistic AppendEntries
    # batches may be in flight beyond a follower's acked match before the
    # leader stalls and re-sends (core/step.py Phase 9).  The analog of
    # the reference's MaxInflightMsgs: 256 (raft.go:158) — much smaller
    # here because one "message" is an E-entry batch re-sent every tick.
    max_inflight_msgs: int = 4

    # K: capacity of the per-group term-transition table (core/state.py
    # tbl_pos/tbl_term).  Terms are monotone along a raft log and change
    # only at elections, so the table of the last K (start, term)
    # transitions answers every term-of-position read the step needs in
    # O(K) — replacing O(W) one-hot ring reads that profiled as ~70% of
    # the TPU tick.  Positions older than the oldest retained transition
    # fall back to the host catch-up path (same contract as falling out
    # of the W ring).
    term_table_slots: int = 8

    # Commit-advance kernel: "point" (etcd's maybeCommit shortcut — check
    # only the quorum index), "windowed" (full masked scan of the ring,
    # ops/commit_scan.py), or "pallas" (hand-written TPU kernel,
    # ops/pallas_quorum.py).  All are safe; they differ in how eagerly an
    # old-term quorum index commits and in lowering strategy.
    commit_rule: str = "point"

    # WAL segment rotation threshold (bytes): the durable log is a
    # directory of bounded files so compaction can unlink whole segments
    # instead of rewriting live data (storage/wal.py; etcd/wal's segment
    # dir as opened at reference raft.go:99-117).
    wal_segment_bytes: int = WAL_SEGMENT_BYTES_DEFAULT

    # Maintain the [G, W] term ring on device.  With every hot-path term
    # read served by the O(K) transition table, the ring is only needed
    # by the windowed/pallas commit rules and by test oracles; the
    # benchmark's fused "point" configuration drops it (the ring WRITE
    # fills were ~40% of the remaining device tick at G=32k).  When
    # False, log_term is kept as a [G, 1] stub so the state pytree keeps
    # its shape.
    keep_ring: bool = True

    # FALSIFICATION ONLY (chaos/run.py transfer family): deliberately
    # break leadership transfer by dropping the catch-up gate AND
    # stepping the old leader down the instant the grant fires — the
    # thesis-§3.10 mistake of deposing the leader before the target's
    # log caught up.  The transfer availability invariant must CATCH
    # this; the flag exists so the harness can prove it does.  Static
    # w.r.t. jit like every other field: when False (always, outside
    # the falsification leg) the compiled program is the shipping
    # kernel, bit for bit.
    unsafe_transfer: bool = False

    # -- Quorum geometry (flexible quorums + witnesses) ----------------
    #
    # Howard & Mortier's FPaxos bridge ported into the batched kernels:
    # the write quorum (AppendEntries acks for commit, lease-clock
    # confirmation) and the election quorum (prevote/vote tallies) may
    # be sized independently, as long as every write quorum intersects
    # every election quorum (W + E > N) — a new leader's election
    # quorum then always contains at least one peer of every committed
    # write's quorum, so the log-completeness argument survives.
    # Unlike FPaxos ballots, raft terms are SHARED across candidates
    # (one vote per term), so election quorums must also pairwise
    # intersect (2E > N) or two candidates can win the same term.
    #
    # None = majority (N//2 + 1): the default geometry, under which the
    # compiled step program is bit-identical to a config without these
    # fields (the chaos digest pin).  Explicit sizes apply to a FULL
    # voter mask; a reduced mask (mid membership change) falls back to
    # its own majority — re-validated across joint configs by
    # membership/manager.py.
    write_quorum: "int | None" = None
    election_quorum: "int | None" = None

    # Witness peers (0-based slot ids): vote, grant prevotes, append
    # and fsync WAL — full quorum citizens for durability and election
    # math — but never campaign, never lead, own no SQLite shard, and
    # never serve any read mode.  Cheap durability: a 2-voter+1-witness
    # group pays two state-machine apply streams, not three.  None/()
    # keeps the compiled program bit-identical to the default.
    witnesses: "tuple | None" = None

    # FALSIFICATION ONLY (chaos/run.py quorum family): skip the quorum
    # intersection validation above, so a deliberately non-intersecting
    # geometry (W + E <= N) can be compiled and the chaos invariants
    # (single leader per term, durability ledger) proven to CATCH the
    # divergence it allows.
    unsafe_quorum_geometry: bool = False

    # FALSIFICATION ONLY (chaos/run.py quorum family): witness peers
    # skip the Phase-2b in-lease prevote refusal while their append
    # acks still count toward the lease clock — the "witness as an
    # always-available tiebreaker" mistake, which lets an election
    # complete inside a live lease.  The read-linearizability invariant
    # must CATCH the stale lease read this opens.
    unsafe_witness_lease: bool = False

    seed: int = 0

    def __post_init__(self):
        if self.num_peers < 1:
            raise ValueError("num_peers must be >= 1")
        if self.num_groups < 1:
            raise ValueError("num_groups must be >= 1")
        # The flow-control formula in core/step.py reserves 2*E slots of
        # headroom; require strictly more so leaders can always admit work.
        if self.log_window < 4 * self.max_entries_per_msg:
            raise ValueError("log_window must be >= 4*max_entries_per_msg")
        if self.election_ticks <= 2 * self.heartbeat_ticks:
            raise ValueError("election_ticks must be > 2*heartbeat_ticks")
        if self.commit_rule not in ("point", "windowed", "pallas"):
            raise ValueError(f"unknown commit_rule {self.commit_rule!r}")
        if self.initial_voters is not None:
            vs = tuple(self.initial_voters)
            if not vs:
                raise ValueError("initial_voters must name >= 1 voter")
            if any(not 0 <= v < self.num_peers for v in vs):
                raise ValueError("initial_voters out of peer-slot range")
            if len(set(vs)) != len(vs):
                raise ValueError("initial_voters has duplicates")
        if not self.keep_ring and self.commit_rule != "point":
            raise ValueError(
                f"commit_rule {self.commit_rule!r} scans the term ring; "
                "it requires keep_ring=True")
        if self.lease_ticks < 0:
            raise ValueError("lease_ticks must be >= 0")
        if self.max_clock_skew < 0:
            raise ValueError("max_clock_skew must be >= 0")
        if self.lease_ticks and not self.prevote:
            # The lease's exclusion window IS the prevote in-lease
            # refusal: without it a fast-clocked peer can assemble a
            # quorum inside the lease and serve stale reads.
            raise ValueError("lease_ticks > 0 requires prevote=True")
        n = self.num_peers
        for name, q in (("write_quorum", self.write_quorum),
                        ("election_quorum", self.election_quorum)):
            if q is not None and not 1 <= q <= n:
                raise ValueError(f"{name} must be in [1, num_peers]")
        if not self.unsafe_quorum_geometry:
            w, e = self.write_size, self.election_size
            if w + e <= n:
                # Intersection (FPaxos §3): a new leader's election
                # quorum must overlap every committed write's quorum.
                raise ValueError(
                    f"write_quorum ({w}) + election_quorum ({e}) must "
                    f"exceed num_peers ({n}) — non-intersecting quorum "
                    "geometry loses committed writes")
            if 2 * e <= n:
                # Raft terms are shared: two election quorums must
                # intersect or two candidates can win one term.
                raise ValueError(
                    f"2 * election_quorum ({e}) must exceed num_peers "
                    f"({n}) — disjoint election quorums break single "
                    "leader per term")
        if self.witnesses is not None:
            ws = tuple(self.witnesses)
            if any(not 0 <= w < n for w in ws):
                raise ValueError("witnesses out of peer-slot range")
            if len(set(ws)) != len(ws):
                raise ValueError("witnesses has duplicates")
            voters = set(self.initial_voters
                         if self.initial_voters is not None
                         else range(n))
            if not set(ws) <= voters:
                # A witness's whole job is to vote and persist; a
                # non-voting witness is just a dead slot.
                raise ValueError("witnesses must be voters")
            if not voters - set(ws):
                raise ValueError(
                    "at least one voter must be a non-witness "
                    "(someone has to lead and apply)")

    @property
    def quorum(self) -> int:
        return self.num_peers // 2 + 1

    @property
    def write_size(self) -> int:
        """Write/commit/lease quorum size (explicit, else majority)."""
        return self.write_quorum if self.write_quorum is not None \
            else self.quorum

    @property
    def election_size(self) -> int:
        """Prevote/vote quorum size (explicit, else majority)."""
        return self.election_quorum if self.election_quorum is not None \
            else self.quorum

    @property
    def default_geometry(self) -> bool:
        """True when both quorums are plain majorities and no witnesses
        are configured: the compiled step program must then be
        bit-identical to one without the geometry fields at all."""
        return (self.write_quorum is None
                and self.election_quorum is None
                and not self.witnesses)

    @property
    def witness_set(self) -> frozenset:
        return frozenset(self.witnesses or ())

    @property
    def static_full_voters(self) -> bool:
        """True when every peer slot is a voter AND that cannot change:
        the step may then use the fixed-quorum kernels (see
        dynamic_membership)."""
        return self.initial_voters is None and not self.dynamic_membership
