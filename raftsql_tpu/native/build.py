"""Build + load the native components (ctypes, on-demand g++ compile).

pybind11 is not available in this environment, so the native pieces expose
a plain C ABI consumed through ctypes.  The shared object is compiled
next to the source on first use and cached by source mtime; failures of
any kind (no compiler, read-only checkout) degrade to the pure-Python
implementations.

Set RAFTSQL_TPU_NATIVE=0 to force the Python fallbacks.
"""
from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import tempfile
import threading

log = logging.getLogger("raftsql_tpu.native")

_DIR = os.path.dirname(os.path.abspath(__file__))
_lock = threading.Lock()
_cache: dict = {}


def _compile(src: str, dest: str, link_args: tuple) -> bool:
    """Compile `src` to `dest` atomically (tmp + rename, so concurrent
    processes never open a half-written artifact); True on success,
    warning + False on any failure, temp never leaked."""
    fd, tmp = tempfile.mkstemp(dir=_DIR)
    os.close(fd)
    try:
        cmd = ["g++", "-O2", "-std=c++17", *link_args, "-o", tmp, src]
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=120)
        except (OSError, subprocess.TimeoutExpired) as e:
            log.warning("native build unavailable (%s); using Python "
                        "fallback", e)
            return False
        if proc.returncode != 0:
            log.warning("native build failed; using Python fallback:\n%s",
                        proc.stderr)
            return False
        os.chmod(tmp, 0o755)
        os.replace(tmp, dest)
        return True
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def _load(name: str):
    """Compile (if stale) and dlopen native/<name>.cc -> CDLL or None."""
    if os.environ.get("RAFTSQL_TPU_NATIVE", "1") == "0":
        return None
    with _lock:
        if name in _cache:
            return _cache[name]
        src = os.path.join(_DIR, f"{name}.cc")
        so = os.path.join(_DIR, f"_native_{name}.so")
        lib = None
        try:
            if not os.path.isfile(so) or \
                    os.path.getmtime(so) < os.path.getmtime(src):
                if not _compile(src, so, ("-shared", "-fPIC")):
                    _cache[name] = None
                    return None
            lib = ctypes.CDLL(so)
        except OSError as e:
            log.warning("native %s load failed (%s); Python fallback",
                        name, e)
            lib = None
        _cache[name] = lib
        return lib


def build_http_load():
    """Compile native/http_load.cc into a standalone load-generator
    binary (the bench harness's `wrk`); returns its path, or None when
    the toolchain is unavailable (callers fall back to the Python
    client threads)."""
    if os.environ.get("RAFTSQL_TPU_NATIVE", "1") == "0":
        return None
    src = os.path.join(_DIR, "http_load.cc")
    exe = os.path.join(_DIR, "_http_load")
    with _lock:
        if "http_load" in _cache:
            return _cache["http_load"]
        path = exe
        try:
            if not os.path.isfile(exe) or \
                    os.path.getmtime(exe) < os.path.getmtime(src):
                if not _compile(src, exe, ()):
                    path = None
        except OSError as e:
            log.warning("http_load build unavailable (%s)", e)
            path = None
        _cache["http_load"] = path
        return path


# Sanitizer build variants for the native WAL stress harness
# (wal_stress.cc drives 4 threads of appends/hardstate/compact/
# snapshot/sync on one handle).  `make native-sanitize` builds + runs
# the asan and ubsan variants; the existing `make tsan` target covers
# thread.  -O1 keeps stacks honest in reports; -fno-omit-frame-pointer
# makes asan traces readable; -fno-sanitize-recover turns every ubsan
# diagnostic into a nonzero exit so CI cannot scroll past one.
SANITIZERS = {
    "asan": ("-pthread", "-fsanitize=address",
             "-fno-omit-frame-pointer"),
    "ubsan": ("-pthread", "-fsanitize=undefined",
              "-fno-sanitize-recover=all"),
    "tsan": ("-pthread", "-fsanitize=thread"),
}


def build_wal_stress(sanitizer: str):
    """Compile the WAL stress binary under `sanitizer` (a SANITIZERS
    key); returns the executable path, or None when the toolchain is
    unavailable (callers degrade to a skip — hosts without g++ are
    covered by the Python WAL backend)."""
    flags = SANITIZERS[sanitizer]
    srcs = [os.path.join(_DIR, "wal_stress.cc"),
            os.path.join(_DIR, "wal.cc")]
    exe = os.path.join(_DIR, f"_wal_stress_{sanitizer}")
    with _lock:
        key = f"wal_stress_{sanitizer}"
        if key in _cache:
            return _cache[key]
        path = exe
        try:
            stale = not os.path.isfile(exe) or any(
                os.path.getmtime(exe) < os.path.getmtime(s)
                for s in srcs)
            if stale and not _compile(
                    srcs[0], exe,
                    ("-O1", "-g", *flags, "-fPIC", srcs[1])):
                path = None
        except OSError as e:
            log.warning("wal_stress %s build unavailable (%s)",
                        sanitizer, e)
            path = None
        _cache[key] = path
        return path


def load_native_plog():
    """ctypes handle to the native payload log + combined walplog entry
    points (same shared object as the WAL), or None."""
    lib = _load("wal")
    if lib is None:
        return None
    c = ctypes
    try:
        lib.plog_new.restype = c.c_void_p
        lib.plog_new.argtypes = [c.c_uint32]
        lib.plog_free.restype = None
        lib.plog_free.argtypes = [c.c_void_p]
        for fn in ("plog_length", "plog_start", "plog_start_term"):
            f = getattr(lib, fn)
            f.restype = c.c_uint64
            f.argtypes = [c.c_void_p, c.c_uint32]
        lib.plog_set_start.restype = c.c_int
        lib.plog_set_start.argtypes = [c.c_void_p, c.c_uint32,
                                       c.c_uint64, c.c_uint64]
        lib.plog_term_of.restype = c.c_uint64
        lib.plog_term_of.argtypes = [c.c_void_p, c.c_uint32, c.c_uint64]
        lib.plog_compact.restype = c.c_int
        lib.plog_compact.argtypes = [c.c_void_p, c.c_uint32, c.c_uint64,
                                     c.c_uint64]
        lib.plog_put_range.restype = c.c_int
        lib.plog_put_range.argtypes = [
            c.c_void_p, c.c_uint32, c.c_uint64, c.c_uint32,
            c.POINTER(c.c_uint64), c.c_char_p, c.POINTER(c.c_uint32),
            c.c_int64]
        lib.plog_range_bytes.restype = c.c_uint64
        lib.plog_range_bytes.argtypes = [c.c_void_p, c.c_uint32,
                                         c.c_uint64, c.c_uint32]
        lib.plog_read_range.restype = c.c_int
        lib.plog_read_range.argtypes = [
            c.c_void_p, c.c_uint32, c.c_uint64, c.c_uint32,
            c.POINTER(c.c_uint8), c.POINTER(c.c_uint32),
            c.POINTER(c.c_uint64)]
        lib.plog_ranges_bytes.restype = c.c_uint64
        lib.plog_ranges_bytes.argtypes = [
            c.c_void_p, c.c_uint32, c.POINTER(c.c_uint32),
            c.POINTER(c.c_uint64), c.POINTER(c.c_uint32)]
        lib.plog_read_groups.restype = c.c_int
        lib.plog_read_groups.argtypes = [
            c.c_void_p, c.c_uint32, c.POINTER(c.c_uint32),
            c.POINTER(c.c_uint64), c.POINTER(c.c_uint32),
            c.POINTER(c.c_uint8), c.POINTER(c.c_uint32)]
        lib.walplog_put_uniform.restype = c.c_int
        lib.walplog_put_uniform.argtypes = [
            c.c_void_p, c.c_void_p, c.c_uint32,
            c.POINTER(c.c_uint32), c.POINTER(c.c_uint64),
            c.POINTER(c.c_uint32), c.POINTER(c.c_uint64),
            c.c_char_p, c.POINTER(c.c_uint32), c.c_uint32]
        lib.walplog_mirror_all.restype = c.c_int
        lib.walplog_mirror_all.argtypes = [
            c.POINTER(c.c_void_p), c.POINTER(c.c_void_p), c.c_uint32,
            c.POINTER(c.c_uint32), c.POINTER(c.c_uint32),
            c.POINTER(c.c_uint32), c.POINTER(c.c_uint64),
            c.POINTER(c.c_uint32), c.POINTER(c.c_int64),
            c.POINTER(c.c_uint64), c.POINTER(c.c_uint32)]
        lib.kv_new.restype = c.c_void_p
        lib.kv_new.argtypes = [c.c_uint32]
        lib.kv_free.restype = None
        lib.kv_free.argtypes = [c.c_void_p]
        lib.kv_apply_plog.restype = c.c_uint64
        lib.kv_apply_plog.argtypes = [
            c.c_void_p, c.c_void_p, c.c_uint32,
            c.POINTER(c.c_uint32), c.POINTER(c.c_uint64),
            c.POINTER(c.c_uint32), c.POINTER(c.c_uint64)]
        lib.kv_applied.restype = c.c_uint64
        lib.kv_applied.argtypes = [c.c_void_p, c.c_uint32]
        lib.kv_count.restype = c.c_uint64
        lib.kv_count.argtypes = [c.c_void_p, c.c_uint32]
        lib.kv_get.restype = c.c_int64
        lib.kv_get.argtypes = [
            c.c_void_p, c.c_uint32, c.c_char_p, c.c_uint32,
            c.POINTER(c.c_uint8), c.c_uint32]
    except AttributeError as e:     # pragma: no cover - stale build
        log.warning("native plog ABI missing (%s); Python fallback", e)
        return None
    return lib


def load_native_wal():
    """ctypes handle to the WAL fast path, or None."""
    lib = _load("wal")
    if lib is None:
        return None
    try:
        lib.wal_open.restype = ctypes.c_void_p
        lib.wal_open.argtypes = [ctypes.c_char_p]
        lib.wal_append_entry.restype = ctypes.c_int
        lib.wal_append_entry.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint64,
            ctypes.c_uint64, ctypes.c_char_p, ctypes.c_uint32]
        lib.wal_append_entries.restype = ctypes.c_int
        lib.wal_append_entries.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint32)]
        lib.wal_append_ranges.restype = ctypes.c_int
        lib.wal_append_ranges.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint32)]
        lib.wal_set_hardstate.restype = ctypes.c_int
        lib.wal_set_hardstate.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint64,
            ctypes.c_int64, ctypes.c_uint64]
        lib.wal_set_hardstates.restype = ctypes.c_int
        lib.wal_set_hardstates.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_uint64)]
        lib.wal_set_snapshot.restype = ctypes.c_int
        lib.wal_set_snapshot.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint64,
            ctypes.c_uint64]
        lib.wal_epoch.restype = ctypes.c_int
        lib.wal_epoch.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint8]
        lib.wal_set_compact.restype = ctypes.c_int
        lib.wal_set_compact.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint64,
            ctypes.c_uint64]
        lib.wal_sync.restype = ctypes.c_int
        lib.wal_sync.argtypes = [ctypes.c_void_p]
        lib.wal_close.restype = ctypes.c_int
        lib.wal_close.argtypes = [ctypes.c_void_p]
    except AttributeError as e:     # pragma: no cover - corrupt build
        log.warning("native wal ABI mismatch (%s); Python fallback", e)
        return None
    return lib
