// Native WAL fast path — the C++ runtime piece of the storage layer.
//
// The reference's durability layer is vendored etcd/wal (Go) feeding an
// fsync before peer sends (reference raft.go:227-235).  At 100k groups per
// tick the record-framing CPU cost lands on the host hot loop, so the
// framing + CRC + buffered write path lives here; Python (storage/wal.py)
// keeps the cold paths (open/replay) and falls back to a pure-Python
// writer when this library is unavailable.
//
// Byte format is identical to storage/wal.py:
//   u32 crc32(body) | u32 body_len | body          (little endian)
//   body := u8 type | fields
//     type 1 ENTRY:     u32 group | u64 index | u64 term | bytes data
//     type 2 HARDSTATE: u32 group | u64 term  | i64 vote | u64 commit
//     type 3 SNAPSHOT:  u32 group | u64 index | u64 term
//     type 4 COMPACT:   u32 group | u64 index | u64 term
//     type 5 RANGE:     u32 group | u64 start | u64 term | u32 count
//                       | u32 lens[count] | payload bytes
//
// Build: g++ -O2 -shared -fPIC -o _native_wal.so wal.cc
// ABI: plain C, consumed via ctypes (no pybind11 in this environment).

#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <mutex>
#include <string>
#include <unordered_map>
#include <unistd.h>
#include <vector>

namespace {

uint32_t kCrcTable[256];
struct CrcInit {
  CrcInit() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      kCrcTable[i] = c;
    }
  }
} crc_init;

uint32_t crc32z_update(uint32_t c, const uint8_t* p, size_t n) {
  for (size_t i = 0; i < n; ++i) c = kCrcTable[(c ^ p[i]) & 0xFF] ^ (c >> 8);
  return c;
}

uint32_t crc32z(const uint8_t* p, size_t n) {  // zlib-compatible
  return crc32z_update(0xFFFFFFFFu, p, n) ^ 0xFFFFFFFFu;
}

struct Wal {
  int fd = -1;
  std::vector<uint8_t> buf;  // framed records pending write+fsync
  std::mutex mu;
};

void put_u32(std::vector<uint8_t>& b, uint32_t v) {
  for (int i = 0; i < 4; ++i) b.push_back(uint8_t(v >> (8 * i)));
}
void put_u64(std::vector<uint8_t>& b, uint64_t v) {
  for (int i = 0; i < 8; ++i) b.push_back(uint8_t(v >> (8 * i)));
}

// Frame `body` (already assembled past the header) into w->buf.
void frame(Wal* w, const std::vector<uint8_t>& body) {
  put_u32(w->buf, crc32z(body.data(), body.size()));
  put_u32(w->buf, uint32_t(body.size()));
  w->buf.insert(w->buf.end(), body.begin(), body.end());
}

int flush_locked(Wal* w) {
  size_t off = 0;
  while (off < w->buf.size()) {
    ssize_t n = ::write(w->fd, w->buf.data() + off, w->buf.size() - off);
    if (n < 0) {
      if (errno == EINTR) continue;
      // Drop the consumed prefix so a retry/close cannot re-write bytes
      // already on disk (which would garble the tail with duplicates).
      w->buf.erase(w->buf.begin(), w->buf.begin() + off);
      return -1;
    }
    off += size_t(n);
  }
  w->buf.clear();
  return 0;
}

}  // namespace

extern "C" {

void* wal_open(const char* path) {
  int fd = ::open(path, O_WRONLY | O_CREAT | O_APPEND, 0644);
  if (fd < 0) return nullptr;
  Wal* w = new Wal();
  w->fd = fd;
  w->buf.reserve(1 << 20);
  return w;
}

int wal_append_entry(void* h, uint32_t group, uint64_t index, uint64_t term,
                     const uint8_t* data, uint32_t len) {
  Wal* w = static_cast<Wal*>(h);
  std::vector<uint8_t> body;
  body.reserve(21 + len);
  body.push_back(1);
  put_u32(body, group);
  put_u64(body, index);
  put_u64(body, term);
  if (len) body.insert(body.end(), data, data + len);
  std::lock_guard<std::mutex> lk(w->mu);
  frame(w, body);
  return 0;
}

// Batched append: n records whose data blobs are concatenated in `datas`
// with per-record lengths in `lens`.  One ctypes call per tick, not per
// record.
int wal_append_entries(void* h, uint32_t n, const uint32_t* groups,
                       const uint64_t* indexes, const uint64_t* terms,
                       const uint8_t* datas, const uint32_t* lens) {
  Wal* w = static_cast<Wal*>(h);
  std::lock_guard<std::mutex> lk(w->mu);
  size_t off = 0;
  std::vector<uint8_t> body;
  for (uint32_t i = 0; i < n; ++i) {
    body.clear();
    body.reserve(21 + lens[i]);
    body.push_back(1);
    put_u32(body, groups[i]);
    put_u64(body, indexes[i]);
    put_u64(body, terms[i]);
    if (lens[i]) body.insert(body.end(), datas + off, datas + off + lens[i]);
    off += lens[i];
    frame(w, body);
  }
  return 0;
}

// Range append: one type-5 record per (group, start, term, count) range
// of consecutive entries — the header+CRC amortizes over the whole
// range (the per-entry framing was the durable tick's byte bottleneck).
// Body: u8 5 | u32 group | u64 start | u64 term | u32 count
//       | u32 lens[count] | payload bytes (concatenated).
int wal_append_ranges(void* h, uint32_t n_ranges, const uint32_t* groups,
                      const uint64_t* starts, const uint64_t* terms,
                      const uint32_t* counts, const uint8_t* blob,
                      const uint32_t* lens) {
  Wal* w = static_cast<Wal*>(h);
  std::lock_guard<std::mutex> lk(w->mu);
  size_t blob_off = 0, len_off = 0;
  std::vector<uint8_t> body;
  for (uint32_t r = 0; r < n_ranges; ++r) {
    uint32_t cnt = counts[r];
    size_t bytes = 0;
    for (uint32_t i = 0; i < cnt; ++i) bytes += lens[len_off + i];
    body.clear();
    body.reserve(25 + 4 * size_t(cnt) + bytes);
    body.push_back(5);
    put_u32(body, groups[r]);
    put_u64(body, starts[r]);
    put_u64(body, terms[r]);
    put_u32(body, cnt);
    for (uint32_t i = 0; i < cnt; ++i) put_u32(body, lens[len_off + i]);
    if (bytes)
      body.insert(body.end(), blob + blob_off, blob + blob_off + bytes);
    blob_off += bytes;
    len_off += cnt;
    frame(w, body);
  }
  return 0;
}

int wal_set_snapshot(void* h, uint32_t group, uint64_t index,
                     uint64_t term) {
  Wal* w = static_cast<Wal*>(h);
  std::vector<uint8_t> body;
  body.reserve(21);
  body.push_back(3);
  put_u32(body, group);
  put_u64(body, index);
  put_u64(body, term);
  std::lock_guard<std::mutex> lk(w->mu);
  frame(w, body);
  return 0;
}

// Compaction floor marker (type 4): on replay, entries of `group` at or
// below `index` are dropped while the retained suffix SURVIVES — unlike
// the snapshot marker (type 3), which also clears the suffix because an
// installed state's history may conflict with it.
// Type 6 EPOCH: u8 kind (0 BEGIN / 1 END) | u64 epoch number — the
// multi-step dispatch frame marker (runtime/fused.py); replay ignores
// it, repair_epochs() truncates at an uncommitted BEGIN.
int wal_epoch(void* h, uint64_t no, uint8_t kind) {
  Wal* w = static_cast<Wal*>(h);
  std::vector<uint8_t> body;
  body.reserve(10);
  body.push_back(6);
  body.push_back(kind);
  put_u64(body, no);
  std::lock_guard<std::mutex> lk(w->mu);
  frame(w, body);
  return 0;
}

int wal_set_compact(void* h, uint32_t group, uint64_t index,
                    uint64_t term) {
  Wal* w = static_cast<Wal*>(h);
  std::vector<uint8_t> body;
  body.reserve(21);
  body.push_back(4);
  put_u32(body, group);
  put_u64(body, index);
  put_u64(body, term);
  std::lock_guard<std::mutex> lk(w->mu);
  frame(w, body);
  return 0;
}

int wal_set_hardstate(void* h, uint32_t group, uint64_t term, int64_t vote,
                      uint64_t commit) {
  Wal* w = static_cast<Wal*>(h);
  std::vector<uint8_t> body;
  body.reserve(29);
  body.push_back(2);
  put_u32(body, group);
  put_u64(body, term);
  put_u64(body, uint64_t(vote));
  put_u64(body, commit);
  std::lock_guard<std::mutex> lk(w->mu);
  frame(w, body);
  return 0;
}

// Batched hard states — one call per tick for every group whose
// (term, vote, commit) changed; under saturation that is ALL groups, so
// the per-record Python/ctypes round trip must not be per group.
int wal_set_hardstates(void* h, uint32_t n, const uint32_t* groups,
                       const uint64_t* terms, const int64_t* votes,
                       const uint64_t* commits) {
  Wal* w = static_cast<Wal*>(h);
  std::lock_guard<std::mutex> lk(w->mu);
  std::vector<uint8_t> body;
  for (uint32_t i = 0; i < n; ++i) {
    body.clear();
    body.reserve(29);
    body.push_back(2);
    put_u32(body, groups[i]);
    put_u64(body, terms[i]);
    put_u64(body, uint64_t(votes[i]));
    put_u64(body, commits[i]);
    frame(w, body);
  }
  return 0;
}

// Durable point: write all pending frames, then fdatasync.  Returns 0 on
// success, -1 on error (caller must treat as fatal — the ordering
// invariant is broken if we proceed).
int wal_sync(void* h) {
  Wal* w = static_cast<Wal*>(h);
  std::lock_guard<std::mutex> lk(w->mu);
  if (w->buf.empty()) return 0;
  if (flush_locked(w) != 0) return -1;
  return ::fdatasync(w->fd) == 0 ? 0 : -1;
}

int wal_close(void* h) {
  Wal* w = static_cast<Wal*>(h);
  int rc = 0;
  {
    std::lock_guard<std::mutex> lk(w->mu);
    if (!w->buf.empty() && flush_locked(w) == 0) ::fdatasync(w->fd);
    rc = ::close(w->fd);
  }
  delete w;
  return rc;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Native payload log — the host byte store behind the device's term
// metadata (the C++ counterpart of storage/log.py PayloadLog), plus the
// combined walplog_* entry points the fused runtime's durable tick uses:
// one ctypes call writes a whole tick's WAL records AND payload-log
// ranges for a peer, and one call performs every follower mirror for the
// whole cluster with the read-all-before-write-all ordering the
// same-tick truncation hazard requires (runtime/fused.py module doc).

namespace {

struct PlogGroup {
  std::vector<std::string> datas;
  std::vector<uint64_t> terms;
  uint64_t start = 0;
  uint64_t start_term = 0;
};

struct Plog {
  std::vector<PlogGroup> groups;
  std::mutex mu;
};

// Write [start, start+n) into g (tail-extend fast path, in-place
// overwrite otherwise); truncate to new_len if >= 0.  Returns -1 on a
// gap (callers treat as fatal — indexes must be contiguous).
int plog_put_locked(PlogGroup& pg, uint64_t start, uint32_t n,
                    const uint64_t* terms, const uint8_t* blob,
                    const uint32_t* lens, int64_t new_len) {
  int64_t rel = int64_t(start) - 1 - int64_t(pg.start);
  size_t off = 0;
  if (rel == int64_t(pg.datas.size())) {
    for (uint32_t i = 0; i < n; ++i) {
      pg.datas.emplace_back(reinterpret_cast<const char*>(blob + off),
                            lens[i]);
      pg.terms.push_back(terms[i]);
      off += lens[i];
    }
  } else {
    for (uint32_t i = 0; i < n; ++i) {
      int64_t pos = rel + int64_t(i);
      if (pos < 0) { off += lens[i]; continue; }  // below floor
      if (pos < int64_t(pg.datas.size())) {
        pg.datas[size_t(pos)].assign(
            reinterpret_cast<const char*>(blob + off), lens[i]);
        pg.terms[size_t(pos)] = terms[i];
      } else if (pos == int64_t(pg.datas.size())) {
        pg.datas.emplace_back(reinterpret_cast<const char*>(blob + off),
                              lens[i]);
        pg.terms.push_back(terms[i]);
      } else {
        return -1;
      }
      off += lens[i];
    }
  }
  if (new_len >= 0) {
    int64_t keep = new_len - int64_t(pg.start);
    if (keep < 0) keep = 0;
    if (size_t(keep) < pg.datas.size()) {
      pg.datas.resize(size_t(keep));
      pg.terms.resize(size_t(keep));
    }
  }
  return 0;
}

void wal_entry_locked(Wal* w, std::vector<uint8_t>& body, uint32_t g,
                      uint64_t idx, uint64_t term, const uint8_t* data,
                      uint32_t len) {
  body.clear();
  body.reserve(21 + len);
  body.push_back(1);
  put_u32(body, g);
  put_u64(body, idx);
  put_u64(body, term);
  if (len) body.insert(body.end(), data, data + len);
  frame(w, body);
}

// One type-5 RANGE record (same layout as wal_append_ranges): entries
// at start..start+n-1, all with `term`, lens/payloads concatenated.
void wal_range_locked(Wal* w, std::vector<uint8_t>& body, uint32_t g,
                      uint64_t start, uint64_t term, uint32_t n,
                      const uint32_t* lens, const uint8_t* blob,
                      size_t bytes) {
  body.clear();
  body.reserve(25 + 4 * size_t(n) + bytes);
  body.push_back(5);
  put_u32(body, g);
  put_u64(body, start);
  put_u64(body, term);
  put_u32(body, n);
  for (uint32_t i = 0; i < n; ++i) put_u32(body, lens[i]);
  if (bytes) body.insert(body.end(), blob, blob + bytes);
  frame(w, body);
}

// Gather-framed RANGE: one type-5 record for entries [k0, k1) of
// `datas` (all term `term`), framed DIRECTLY into w->buf — the CRC is
// computed incrementally over head + payloads, so the payload bytes
// are copied exactly once.  Byte-identical to wal_range_locked; used
// by the mirror path, which re-copies every committed byte to P-1
// peers per tick and is memcpy-bound.
void wal_range_gather_locked(Wal* w, std::vector<uint8_t>& head,
                             uint32_t g, uint64_t start, uint64_t term,
                             const std::string* datas, uint32_t k0,
                             uint32_t k1) {
  head.clear();
  head.push_back(5);
  put_u32(head, g);
  put_u64(head, start);
  put_u64(head, term);
  put_u32(head, k1 - k0);
  size_t bytes = 0;
  for (uint32_t k = k0; k < k1; ++k) {
    put_u32(head, uint32_t(datas[k].size()));
    bytes += datas[k].size();
  }
  uint32_t c = crc32z_update(0xFFFFFFFFu, head.data(), head.size());
  for (uint32_t k = k0; k < k1; ++k)
    c = crc32z_update(
        c, reinterpret_cast<const uint8_t*>(datas[k].data()),
        datas[k].size());
  put_u32(w->buf, c ^ 0xFFFFFFFFu);
  put_u32(w->buf, uint32_t(head.size() + bytes));
  w->buf.insert(w->buf.end(), head.begin(), head.end());
  for (uint32_t k = k0; k < k1; ++k)
    w->buf.insert(w->buf.end(), datas[k].begin(), datas[k].end());
}

}  // namespace

extern "C" {

void* plog_new(uint32_t num_groups) {
  Plog* p = new Plog();
  p->groups.resize(num_groups);
  return p;
}

void plog_free(void* h) { delete static_cast<Plog*>(h); }

uint64_t plog_length(void* h, uint32_t g) {
  Plog* p = static_cast<Plog*>(h);
  std::lock_guard<std::mutex> lk(p->mu);
  return p->groups[g].start + p->groups[g].datas.size();
}

uint64_t plog_start(void* h, uint32_t g) {
  Plog* p = static_cast<Plog*>(h);
  std::lock_guard<std::mutex> lk(p->mu);
  return p->groups[g].start;
}

uint64_t plog_start_term(void* h, uint32_t g) {
  Plog* p = static_cast<Plog*>(h);
  std::lock_guard<std::mutex> lk(p->mu);
  return p->groups[g].start_term;
}

int plog_set_start(void* h, uint32_t g, uint64_t start,
                   uint64_t start_term) {
  Plog* p = static_cast<Plog*>(h);
  std::lock_guard<std::mutex> lk(p->mu);
  PlogGroup& pg = p->groups[g];
  if (!pg.datas.empty()) return -1;
  pg.start = start;
  pg.start_term = start_term;
  return 0;
}

// Term of entry idx; idx == 0 -> 0, idx == start -> boundary term,
// below-floor/beyond-tail -> UINT64_MAX (caller decides retry/assert).
uint64_t plog_term_of(void* h, uint32_t g, uint64_t idx) {
  Plog* p = static_cast<Plog*>(h);
  std::lock_guard<std::mutex> lk(p->mu);
  PlogGroup& pg = p->groups[g];
  if (idx == 0) return 0;
  if (idx == pg.start) return pg.start_term;
  if (idx < pg.start || idx > pg.start + pg.terms.size())
    return ~uint64_t(0);
  return pg.terms[size_t(idx - 1 - pg.start)];
}

int plog_compact(void* h, uint32_t g, uint64_t upto,
                 uint64_t boundary_term) {
  Plog* p = static_cast<Plog*>(h);
  std::lock_guard<std::mutex> lk(p->mu);
  PlogGroup& pg = p->groups[g];
  if (upto <= pg.start) return 0;
  size_t drop = size_t(upto - pg.start);
  if (drop > pg.datas.size()) return -1;
  pg.datas.erase(pg.datas.begin(), pg.datas.begin() + drop);
  pg.terms.erase(pg.terms.begin(), pg.terms.begin() + drop);
  pg.start = upto;
  pg.start_term = boundary_term;
  return 0;
}

int plog_put_range(void* h, uint32_t g, uint64_t start, uint32_t n,
                   const uint64_t* terms, const uint8_t* blob,
                   const uint32_t* lens, int64_t new_len) {
  Plog* p = static_cast<Plog*>(h);
  std::lock_guard<std::mutex> lk(p->mu);
  return plog_put_locked(p->groups[g], start, n, terms, blob, lens,
                         new_len);
}

// Two-phase read: total byte size of [start, start+n), then fill.
// Returns UINT64_MAX if the range dips below the floor or past the tail.
uint64_t plog_range_bytes(void* h, uint32_t g, uint64_t start, uint32_t n) {
  Plog* p = static_cast<Plog*>(h);
  std::lock_guard<std::mutex> lk(p->mu);
  PlogGroup& pg = p->groups[g];
  int64_t rel = int64_t(start) - 1 - int64_t(pg.start);
  if (rel < 0 || size_t(rel) + n > pg.datas.size()) return ~uint64_t(0);
  uint64_t total = 0;
  for (uint32_t i = 0; i < n; ++i) total += pg.datas[size_t(rel) + i].size();
  return total;
}

int plog_read_range(void* h, uint32_t g, uint64_t start, uint32_t n,
                    uint8_t* blob_out, uint32_t* lens_out,
                    uint64_t* terms_out) {
  Plog* p = static_cast<Plog*>(h);
  std::lock_guard<std::mutex> lk(p->mu);
  PlogGroup& pg = p->groups[g];
  int64_t rel = int64_t(start) - 1 - int64_t(pg.start);
  if (rel < 0 || size_t(rel) + n > pg.datas.size()) return -1;
  size_t off = 0;
  for (uint32_t i = 0; i < n; ++i) {
    const std::string& d = pg.datas[size_t(rel) + i];
    if (blob_out) std::memcpy(blob_out + off, d.data(), d.size());
    if (lens_out) lens_out[i] = uint32_t(d.size());
    if (terms_out) terms_out[i] = pg.terms[size_t(rel) + i];
    off += d.size();
  }
  return 0;
}

// Batched multi-group read (the publish hot path): total bytes of all
// ranges, then one fill of concatenated payloads + per-entry lens in
// range order.  Returns UINT64_MAX / -1 if any range is unavailable.
uint64_t plog_ranges_bytes(void* h, uint32_t n_ranges,
                           const uint32_t* groups, const uint64_t* starts,
                           const uint32_t* counts) {
  Plog* p = static_cast<Plog*>(h);
  std::lock_guard<std::mutex> lk(p->mu);
  uint64_t total = 0;
  for (uint32_t r = 0; r < n_ranges; ++r) {
    PlogGroup& pg = p->groups[groups[r]];
    int64_t rel = int64_t(starts[r]) - 1 - int64_t(pg.start);
    if (rel < 0 || size_t(rel) + counts[r] > pg.datas.size())
      return ~uint64_t(0);
    for (uint32_t i = 0; i < counts[r]; ++i)
      total += pg.datas[size_t(rel) + i].size();
  }
  return total;
}

int plog_read_groups(void* h, uint32_t n_ranges, const uint32_t* groups,
                     const uint64_t* starts, const uint32_t* counts,
                     uint8_t* blob_out, uint32_t* lens_out) {
  Plog* p = static_cast<Plog*>(h);
  std::lock_guard<std::mutex> lk(p->mu);
  size_t off = 0, li = 0;
  for (uint32_t r = 0; r < n_ranges; ++r) {
    PlogGroup& pg = p->groups[groups[r]];
    int64_t rel = int64_t(starts[r]) - 1 - int64_t(pg.start);
    if (rel < 0 || size_t(rel) + counts[r] > pg.datas.size()) return -1;
    for (uint32_t i = 0; i < counts[r]; ++i) {
      const std::string& d = pg.datas[size_t(rel) + i];
      std::memcpy(blob_out + off, d.data(), d.size());
      lens_out[li++] = uint32_t(d.size());
      off += d.size();
    }
  }
  return 0;
}

// Combined leader-append path: for each range i, write WAL ENTRY records
// AND the payload-log range, all entries of range i sharing terms[i].
// Ranges are (group, start, count) with payload bytes concatenated in
// `blob` / per-entry `lens` in range order.  One call per peer per tick.
//
// `wal_group_bias` is added to the group id of every WAL record (NOT
// the payload-log index): the group-commit layout (storage/wal.py
// GroupCommitWAL) multiplexes all P peers' logical logs into one
// physical record stream by flat id peer*G + g, while each peer's
// payload log stays per-peer and unbiased.
int walplog_put_uniform(void* wal_h, void* plog_h, uint32_t n_ranges,
                        const uint32_t* groups, const uint64_t* starts,
                        const uint32_t* counts, const uint64_t* terms,
                        const uint8_t* blob, const uint32_t* lens,
                        uint32_t wal_group_bias) {
  Wal* w = static_cast<Wal*>(wal_h);
  Plog* p = static_cast<Plog*>(plog_h);
  std::lock_guard<std::mutex> lw(w->mu);
  std::lock_guard<std::mutex> lp(p->mu);
  size_t off = 0, li = 0;
  std::vector<uint64_t> tbuf;
  std::vector<uint8_t> body;
  for (uint32_t r = 0; r < n_ranges; ++r) {
    uint32_t n = counts[r];
    if (n == 0) continue;               // empty runs write nothing
    tbuf.assign(n, terms[r]);
    size_t range_bytes = 0;
    for (uint32_t i = 0; i < n; ++i) range_bytes += lens[li + i];
    wal_range_locked(w, body, groups[r] + wal_group_bias, starts[r],
                     terms[r], n, lens + li, blob + off, range_bytes);
    int rc = plog_put_locked(p->groups[groups[r]], starts[r], n,
                             tbuf.data(), blob + off, lens + li, -1);
    if (rc != 0) return rc;
    off += range_bytes;
    li += n;
  }
  return 0;
}

// Combined mirror path for the WHOLE cluster: phase A reads every
// source range into scratch (so a same-tick truncation or overwrite on
// any source cannot tear any mirror — the read-all-before-write-all
// contract); phase B writes each destination's payload-log range +
// truncation and its WAL ENTRY records.  `wals`/`plogs` are per-peer
// handle arrays; `peer`/`src` index them.
// `wal_biases` (may be null = all zero) is indexed by destination peer
// and added to the group id of that peer's WAL records only — see
// walplog_put_uniform.  Under the group-commit layout every wals[i]
// is the SAME shared handle (one buffer, one mutex, one fd) and the
// bias keeps the multiplexed records per-peer-separable on replay.
int walplog_mirror_all(void** wals, void** plogs, uint32_t n_mirrors,
                       const uint32_t* peer, const uint32_t* src,
                       const uint32_t* groups, const uint64_t* starts,
                       const uint32_t* counts, const int64_t* new_lens,
                       uint64_t* per_peer_bytes,
                       const uint32_t* wal_biases) {
  struct Scratch {
    std::vector<std::string> datas;
    std::vector<uint64_t> terms;
  };
  std::vector<Scratch> scratch(n_mirrors);
  for (uint32_t i = 0; i < n_mirrors; ++i) {
    Plog* sp = static_cast<Plog*>(plogs[src[i]]);
    std::lock_guard<std::mutex> lk(sp->mu);
    PlogGroup& pg = sp->groups[groups[i]];
    int64_t rel = int64_t(starts[i]) - 1 - int64_t(pg.start);
    uint32_t n = counts[i];
    if (n == 0) continue;
    if (rel < 0 || size_t(rel) + n > pg.datas.size()) return -1;
    scratch[i].datas.assign(pg.datas.begin() + rel,
                            pg.datas.begin() + rel + n);
    scratch[i].terms.assign(pg.terms.begin() + rel,
                            pg.terms.begin() + rel + n);
  }
  for (uint32_t i = 0; i < n_mirrors; ++i) {
    Wal* w = static_cast<Wal*>(wals[peer[i]]);
    Plog* dp = static_cast<Plog*>(plogs[peer[i]]);
    uint32_t n = counts[i];
    std::lock_guard<std::mutex> lw(w->mu);
    std::lock_guard<std::mutex> lp(dp->mu);
    PlogGroup& pg = dp->groups[groups[i]];
    int64_t rel = int64_t(starts[i]) - 1 - int64_t(pg.start);
    std::vector<uint8_t> body;
    size_t buf0 = w->buf.size();
    // WAL records as same-term RANGE runs (split at term boundaries —
    // rare: only elections change terms inside a mirrored batch),
    // gather-framed so each payload byte is copied once.
    uint32_t bias = wal_biases ? wal_biases[peer[i]] : 0;
    for (uint32_t k0 = 0; k0 < n;) {
      uint64_t t = scratch[i].terms[k0];
      uint32_t k1 = k0;
      while (k1 < n && scratch[i].terms[k1] == t) ++k1;
      wal_range_gather_locked(w, body, groups[i] + bias, starts[i] + k0,
                              t, scratch[i].datas.data(), k0, k1);
      k0 = k1;
    }
    for (uint32_t k = 0; k < n; ++k) {
      const std::string& d = scratch[i].datas[k];
      int64_t pos = rel + int64_t(k);
      if (pos < 0) continue;
      if (pos < int64_t(pg.datas.size())) {
        pg.datas[size_t(pos)] = d;
        pg.terms[size_t(pos)] = scratch[i].terms[k];
      } else if (pos == int64_t(pg.datas.size())) {
        pg.datas.push_back(d);
        pg.terms.push_back(scratch[i].terms[k]);
      } else {
        return -1;
      }
    }
    // Framed-byte accounting from actual buffer growth (no layout
    // constant to drift from the Python struct definitions).
    if (per_peer_bytes) per_peer_bytes[peer[i]] += w->buf.size() - buf0;
    int64_t nl = new_lens[i];
    if (nl >= 0) {
      int64_t keep = nl - int64_t(pg.start);
      if (keep < 0) keep = 0;
      if (size_t(keep) < pg.datas.size()) {
        pg.datas.resize(size_t(keep));
        pg.terms.resize(size_t(keep));
      }
    }
  }
  return 0;
}

// ---------------------------------------------------------------------------
// Native KV apply plane: the C++ counterpart of models/kv_sm.py, fed
// RANGES straight from the native payload log — committed entries are
// parsed and applied without ever materializing Python objects (the
// measured ceiling of the Python-resident durable path was per-entry
// object handling).  Command grammar matches KVStateMachine.apply:
//   "SET <key> <value>"  (value may contain spaces)
//   "DEL <key>"          (exactly one token after DEL)
// anything else counts as a bad command (reported, not fatal), and an
// entry at or below the group's applied index is skipped (exactly-once
// across replays/installs, KVStateMachine.apply's index guard).

struct Kv {
  std::vector<std::unordered_map<std::string, std::string>> groups;
  std::vector<uint64_t> applied;
  std::mutex mu;
};

void* kv_new(uint32_t num_groups) {
  Kv* kv = new Kv();
  kv->groups.resize(num_groups);
  kv->applied.assign(num_groups, 0);
  return kv;
}

void kv_free(void* h) { delete static_cast<Kv*>(h); }

// Apply plog entries [starts[r], starts[r]+counts[r]) of groups[r] for
// every range; empty payloads (no-op entries) skipped.  Returns the
// number applied, or UINT64_MAX when a committed index falls outside
// the payload-log window (the wrapper raises, matching the Python
// path's "payload log shorter than commit" RuntimeError) — work done
// before the fault IS recorded in applied[], so nothing double-applies
// on retry.  Bad commands are counted into *bad (may be null).
// Holds both locks for the batch: the caller (the fused runtime's
// publish, or its overlap window) owns the tick thread, so there is no
// producer to stall.
uint64_t kv_apply_plog(void* kv_h, void* plog_h, uint32_t n_ranges,
                       const uint32_t* groups, const uint64_t* starts,
                       const uint32_t* counts, uint64_t* bad) {
  Kv* kv = static_cast<Kv*>(kv_h);
  Plog* p = static_cast<Plog*>(plog_h);
  std::lock_guard<std::mutex> lk(kv->mu);
  std::lock_guard<std::mutex> lp(p->mu);
  uint64_t done = 0, nbad = 0;
  for (uint32_t r = 0; r < n_ranges; ++r) {
    uint32_t g = groups[r];
    PlogGroup& pg = p->groups[g];
    auto& map = kv->groups[g];
    uint64_t ap = kv->applied[g];
    for (uint32_t i = 0; i < counts[r]; ++i) {
      uint64_t idx = starts[r] + i;
      if (idx <= ap) continue;
      int64_t rel = int64_t(idx) - 1 - int64_t(pg.start);
      if (rel < 0 || size_t(rel) >= pg.datas.size()) {
        kv->applied[g] = ap;
        if (bad) *bad += nbad;
        return UINT64_MAX;
      }
      const std::string& d = pg.datas[size_t(rel)];
      ap = idx;
      if (d.empty()) continue;                   // no-op entry
      if (d.size() > 4 && !d.compare(0, 4, "SET ")) {
        size_t sp = d.find(' ', 4);
        if (sp != std::string::npos && sp + 1 <= d.size()) {
          map[d.substr(4, sp - 4)] = d.substr(sp + 1);
          ++done;
          continue;
        }
      } else if (d.size() >= 4 && !d.compare(0, 4, "DEL ")) {
        // "DEL <key>" with exactly one token after DEL; an empty key
        // is valid (split(" ", 2) parity with KVStateMachine.apply).
        if (d.find(' ', 4) == std::string::npos) {
          map.erase(d.substr(4));
          ++done;
          continue;
        }
      }
      ++nbad;
    }
    kv->applied[g] = ap;
  }
  if (bad) *bad += nbad;
  return done;
}

uint64_t kv_applied(void* h, uint32_t g) {
  Kv* kv = static_cast<Kv*>(h);
  std::lock_guard<std::mutex> lk(kv->mu);
  return kv->applied[g];
}

uint64_t kv_count(void* h, uint32_t g) {
  Kv* kv = static_cast<Kv*>(h);
  std::lock_guard<std::mutex> lk(kv->mu);
  return kv->groups[g].size();
}

// Value of `key` into out (cap bytes); returns the value length, or -1
// if absent.  A return > cap means the buffer was too small (caller
// retries with a bigger one).
int64_t kv_get(void* h, uint32_t g, const uint8_t* key, uint32_t klen,
               uint8_t* out, uint32_t cap) {
  Kv* kv = static_cast<Kv*>(h);
  std::lock_guard<std::mutex> lk(kv->mu);
  auto& map = kv->groups[g];
  auto it = map.find(std::string(reinterpret_cast<const char*>(key),
                                 klen));
  if (it == map.end()) return -1;
  const std::string& v = it->second;
  if (v.size() <= cap && cap) memcpy(out, v.data(), v.size());
  return int64_t(v.size());
}

}  // extern "C"
