// Native WAL fast path — the C++ runtime piece of the storage layer.
//
// The reference's durability layer is vendored etcd/wal (Go) feeding an
// fsync before peer sends (reference raft.go:227-235).  At 100k groups per
// tick the record-framing CPU cost lands on the host hot loop, so the
// framing + CRC + buffered write path lives here; Python (storage/wal.py)
// keeps the cold paths (open/replay) and falls back to a pure-Python
// writer when this library is unavailable.
//
// Byte format is identical to storage/wal.py:
//   u32 crc32(body) | u32 body_len | body          (little endian)
//   body := u8 type | fields
//     type 1 ENTRY:     u32 group | u64 index | u64 term | bytes data
//     type 2 HARDSTATE: u32 group | u64 term  | i64 vote | u64 commit
//     type 3 SNAPSHOT:  u32 group | u64 index | u64 term
//     type 4 COMPACT:   u32 group | u64 index | u64 term
//
// Build: g++ -O2 -shared -fPIC -o _native_wal.so wal.cc
// ABI: plain C, consumed via ctypes (no pybind11 in this environment).

#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <mutex>
#include <unistd.h>
#include <vector>

namespace {

uint32_t kCrcTable[256];
struct CrcInit {
  CrcInit() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      kCrcTable[i] = c;
    }
  }
} crc_init;

uint32_t crc32z(const uint8_t* p, size_t n) {  // zlib-compatible
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; ++i) c = kCrcTable[(c ^ p[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

struct Wal {
  int fd = -1;
  std::vector<uint8_t> buf;  // framed records pending write+fsync
  std::mutex mu;
};

void put_u32(std::vector<uint8_t>& b, uint32_t v) {
  for (int i = 0; i < 4; ++i) b.push_back(uint8_t(v >> (8 * i)));
}
void put_u64(std::vector<uint8_t>& b, uint64_t v) {
  for (int i = 0; i < 8; ++i) b.push_back(uint8_t(v >> (8 * i)));
}

// Frame `body` (already assembled past the header) into w->buf.
void frame(Wal* w, const std::vector<uint8_t>& body) {
  put_u32(w->buf, crc32z(body.data(), body.size()));
  put_u32(w->buf, uint32_t(body.size()));
  w->buf.insert(w->buf.end(), body.begin(), body.end());
}

int flush_locked(Wal* w) {
  size_t off = 0;
  while (off < w->buf.size()) {
    ssize_t n = ::write(w->fd, w->buf.data() + off, w->buf.size() - off);
    if (n < 0) {
      if (errno == EINTR) continue;
      // Drop the consumed prefix so a retry/close cannot re-write bytes
      // already on disk (which would garble the tail with duplicates).
      w->buf.erase(w->buf.begin(), w->buf.begin() + off);
      return -1;
    }
    off += size_t(n);
  }
  w->buf.clear();
  return 0;
}

}  // namespace

extern "C" {

void* wal_open(const char* path) {
  int fd = ::open(path, O_WRONLY | O_CREAT | O_APPEND, 0644);
  if (fd < 0) return nullptr;
  Wal* w = new Wal();
  w->fd = fd;
  w->buf.reserve(1 << 20);
  return w;
}

int wal_append_entry(void* h, uint32_t group, uint64_t index, uint64_t term,
                     const uint8_t* data, uint32_t len) {
  Wal* w = static_cast<Wal*>(h);
  std::vector<uint8_t> body;
  body.reserve(21 + len);
  body.push_back(1);
  put_u32(body, group);
  put_u64(body, index);
  put_u64(body, term);
  if (len) body.insert(body.end(), data, data + len);
  std::lock_guard<std::mutex> lk(w->mu);
  frame(w, body);
  return 0;
}

// Batched append: n records whose data blobs are concatenated in `datas`
// with per-record lengths in `lens`.  One ctypes call per tick, not per
// record.
int wal_append_entries(void* h, uint32_t n, const uint32_t* groups,
                       const uint64_t* indexes, const uint64_t* terms,
                       const uint8_t* datas, const uint32_t* lens) {
  Wal* w = static_cast<Wal*>(h);
  std::lock_guard<std::mutex> lk(w->mu);
  size_t off = 0;
  std::vector<uint8_t> body;
  for (uint32_t i = 0; i < n; ++i) {
    body.clear();
    body.reserve(21 + lens[i]);
    body.push_back(1);
    put_u32(body, groups[i]);
    put_u64(body, indexes[i]);
    put_u64(body, terms[i]);
    if (lens[i]) body.insert(body.end(), datas + off, datas + off + lens[i]);
    off += lens[i];
    frame(w, body);
  }
  return 0;
}

// Range append: one type-5 record per (group, start, term, count) range
// of consecutive entries — the header+CRC amortizes over the whole
// range (the per-entry framing was the durable tick's byte bottleneck).
// Body: u8 5 | u32 group | u64 start | u64 term | u32 count
//       | u32 lens[count] | payload bytes (concatenated).
int wal_append_ranges(void* h, uint32_t n_ranges, const uint32_t* groups,
                      const uint64_t* starts, const uint64_t* terms,
                      const uint32_t* counts, const uint8_t* blob,
                      const uint32_t* lens) {
  Wal* w = static_cast<Wal*>(h);
  std::lock_guard<std::mutex> lk(w->mu);
  size_t blob_off = 0, len_off = 0;
  std::vector<uint8_t> body;
  for (uint32_t r = 0; r < n_ranges; ++r) {
    uint32_t cnt = counts[r];
    size_t bytes = 0;
    for (uint32_t i = 0; i < cnt; ++i) bytes += lens[len_off + i];
    body.clear();
    body.reserve(25 + 4 * size_t(cnt) + bytes);
    body.push_back(5);
    put_u32(body, groups[r]);
    put_u64(body, starts[r]);
    put_u64(body, terms[r]);
    put_u32(body, cnt);
    for (uint32_t i = 0; i < cnt; ++i) put_u32(body, lens[len_off + i]);
    if (bytes)
      body.insert(body.end(), blob + blob_off, blob + blob_off + bytes);
    blob_off += bytes;
    len_off += cnt;
    frame(w, body);
  }
  return 0;
}

int wal_set_snapshot(void* h, uint32_t group, uint64_t index,
                     uint64_t term) {
  Wal* w = static_cast<Wal*>(h);
  std::vector<uint8_t> body;
  body.reserve(21);
  body.push_back(3);
  put_u32(body, group);
  put_u64(body, index);
  put_u64(body, term);
  std::lock_guard<std::mutex> lk(w->mu);
  frame(w, body);
  return 0;
}

// Compaction floor marker (type 4): on replay, entries of `group` at or
// below `index` are dropped while the retained suffix SURVIVES — unlike
// the snapshot marker (type 3), which also clears the suffix because an
// installed state's history may conflict with it.
int wal_set_compact(void* h, uint32_t group, uint64_t index,
                    uint64_t term) {
  Wal* w = static_cast<Wal*>(h);
  std::vector<uint8_t> body;
  body.reserve(21);
  body.push_back(4);
  put_u32(body, group);
  put_u64(body, index);
  put_u64(body, term);
  std::lock_guard<std::mutex> lk(w->mu);
  frame(w, body);
  return 0;
}

int wal_set_hardstate(void* h, uint32_t group, uint64_t term, int64_t vote,
                      uint64_t commit) {
  Wal* w = static_cast<Wal*>(h);
  std::vector<uint8_t> body;
  body.reserve(29);
  body.push_back(2);
  put_u32(body, group);
  put_u64(body, term);
  put_u64(body, uint64_t(vote));
  put_u64(body, commit);
  std::lock_guard<std::mutex> lk(w->mu);
  frame(w, body);
  return 0;
}

// Batched hard states — one call per tick for every group whose
// (term, vote, commit) changed; under saturation that is ALL groups, so
// the per-record Python/ctypes round trip must not be per group.
int wal_set_hardstates(void* h, uint32_t n, const uint32_t* groups,
                       const uint64_t* terms, const int64_t* votes,
                       const uint64_t* commits) {
  Wal* w = static_cast<Wal*>(h);
  std::lock_guard<std::mutex> lk(w->mu);
  std::vector<uint8_t> body;
  for (uint32_t i = 0; i < n; ++i) {
    body.clear();
    body.reserve(29);
    body.push_back(2);
    put_u32(body, groups[i]);
    put_u64(body, terms[i]);
    put_u64(body, uint64_t(votes[i]));
    put_u64(body, commits[i]);
    frame(w, body);
  }
  return 0;
}

// Durable point: write all pending frames, then fdatasync.  Returns 0 on
// success, -1 on error (caller must treat as fatal — the ordering
// invariant is broken if we proceed).
int wal_sync(void* h) {
  Wal* w = static_cast<Wal*>(h);
  std::lock_guard<std::mutex> lk(w->mu);
  if (w->buf.empty()) return 0;
  if (flush_locked(w) != 0) return -1;
  return ::fdatasync(w->fd) == 0 ? 0 : -1;
}

int wal_close(void* h) {
  Wal* w = static_cast<Wal*>(h);
  int rc = 0;
  {
    std::lock_guard<std::mutex> lk(w->mu);
    if (!w->buf.empty() && flush_locked(w) == 0) ::fdatasync(w->fd);
    rc = ::close(w->fd);
  }
  delete w;
  return rc;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Native payload log — the host byte store behind the device's term
// metadata (the C++ counterpart of storage/log.py PayloadLog), plus the
// combined walplog_* entry points the fused runtime's durable tick uses:
// one ctypes call writes a whole tick's WAL records AND payload-log
// ranges for a peer, and one call performs every follower mirror for the
// whole cluster with the read-all-before-write-all ordering the
// same-tick truncation hazard requires (runtime/fused.py module doc).

namespace {

struct PlogGroup {
  std::vector<std::string> datas;
  std::vector<uint64_t> terms;
  uint64_t start = 0;
  uint64_t start_term = 0;
};

struct Plog {
  std::vector<PlogGroup> groups;
  std::mutex mu;
};

// Write [start, start+n) into g (tail-extend fast path, in-place
// overwrite otherwise); truncate to new_len if >= 0.  Returns -1 on a
// gap (callers treat as fatal — indexes must be contiguous).
int plog_put_locked(PlogGroup& pg, uint64_t start, uint32_t n,
                    const uint64_t* terms, const uint8_t* blob,
                    const uint32_t* lens, int64_t new_len) {
  int64_t rel = int64_t(start) - 1 - int64_t(pg.start);
  size_t off = 0;
  if (rel == int64_t(pg.datas.size())) {
    for (uint32_t i = 0; i < n; ++i) {
      pg.datas.emplace_back(reinterpret_cast<const char*>(blob + off),
                            lens[i]);
      pg.terms.push_back(terms[i]);
      off += lens[i];
    }
  } else {
    for (uint32_t i = 0; i < n; ++i) {
      int64_t pos = rel + int64_t(i);
      if (pos < 0) { off += lens[i]; continue; }  // below floor
      if (pos < int64_t(pg.datas.size())) {
        pg.datas[size_t(pos)].assign(
            reinterpret_cast<const char*>(blob + off), lens[i]);
        pg.terms[size_t(pos)] = terms[i];
      } else if (pos == int64_t(pg.datas.size())) {
        pg.datas.emplace_back(reinterpret_cast<const char*>(blob + off),
                              lens[i]);
        pg.terms.push_back(terms[i]);
      } else {
        return -1;
      }
      off += lens[i];
    }
  }
  if (new_len >= 0) {
    int64_t keep = new_len - int64_t(pg.start);
    if (keep < 0) keep = 0;
    if (size_t(keep) < pg.datas.size()) {
      pg.datas.resize(size_t(keep));
      pg.terms.resize(size_t(keep));
    }
  }
  return 0;
}

void wal_entry_locked(Wal* w, std::vector<uint8_t>& body, uint32_t g,
                      uint64_t idx, uint64_t term, const uint8_t* data,
                      uint32_t len) {
  body.clear();
  body.reserve(21 + len);
  body.push_back(1);
  put_u32(body, g);
  put_u64(body, idx);
  put_u64(body, term);
  if (len) body.insert(body.end(), data, data + len);
  frame(w, body);
}

}  // namespace

extern "C" {

void* plog_new(uint32_t num_groups) {
  Plog* p = new Plog();
  p->groups.resize(num_groups);
  return p;
}

void plog_free(void* h) { delete static_cast<Plog*>(h); }

uint64_t plog_length(void* h, uint32_t g) {
  Plog* p = static_cast<Plog*>(h);
  std::lock_guard<std::mutex> lk(p->mu);
  return p->groups[g].start + p->groups[g].datas.size();
}

uint64_t plog_start(void* h, uint32_t g) {
  Plog* p = static_cast<Plog*>(h);
  std::lock_guard<std::mutex> lk(p->mu);
  return p->groups[g].start;
}

uint64_t plog_start_term(void* h, uint32_t g) {
  Plog* p = static_cast<Plog*>(h);
  std::lock_guard<std::mutex> lk(p->mu);
  return p->groups[g].start_term;
}

int plog_set_start(void* h, uint32_t g, uint64_t start,
                   uint64_t start_term) {
  Plog* p = static_cast<Plog*>(h);
  std::lock_guard<std::mutex> lk(p->mu);
  PlogGroup& pg = p->groups[g];
  if (!pg.datas.empty()) return -1;
  pg.start = start;
  pg.start_term = start_term;
  return 0;
}

// Term of entry idx; idx == 0 -> 0, idx == start -> boundary term,
// below-floor/beyond-tail -> UINT64_MAX (caller decides retry/assert).
uint64_t plog_term_of(void* h, uint32_t g, uint64_t idx) {
  Plog* p = static_cast<Plog*>(h);
  std::lock_guard<std::mutex> lk(p->mu);
  PlogGroup& pg = p->groups[g];
  if (idx == 0) return 0;
  if (idx == pg.start) return pg.start_term;
  if (idx < pg.start || idx > pg.start + pg.terms.size())
    return ~uint64_t(0);
  return pg.terms[size_t(idx - 1 - pg.start)];
}

int plog_compact(void* h, uint32_t g, uint64_t upto,
                 uint64_t boundary_term) {
  Plog* p = static_cast<Plog*>(h);
  std::lock_guard<std::mutex> lk(p->mu);
  PlogGroup& pg = p->groups[g];
  if (upto <= pg.start) return 0;
  size_t drop = size_t(upto - pg.start);
  if (drop > pg.datas.size()) return -1;
  pg.datas.erase(pg.datas.begin(), pg.datas.begin() + drop);
  pg.terms.erase(pg.terms.begin(), pg.terms.begin() + drop);
  pg.start = upto;
  pg.start_term = boundary_term;
  return 0;
}

int plog_put_range(void* h, uint32_t g, uint64_t start, uint32_t n,
                   const uint64_t* terms, const uint8_t* blob,
                   const uint32_t* lens, int64_t new_len) {
  Plog* p = static_cast<Plog*>(h);
  std::lock_guard<std::mutex> lk(p->mu);
  return plog_put_locked(p->groups[g], start, n, terms, blob, lens,
                         new_len);
}

// Two-phase read: total byte size of [start, start+n), then fill.
// Returns UINT64_MAX if the range dips below the floor or past the tail.
uint64_t plog_range_bytes(void* h, uint32_t g, uint64_t start, uint32_t n) {
  Plog* p = static_cast<Plog*>(h);
  std::lock_guard<std::mutex> lk(p->mu);
  PlogGroup& pg = p->groups[g];
  int64_t rel = int64_t(start) - 1 - int64_t(pg.start);
  if (rel < 0 || size_t(rel) + n > pg.datas.size()) return ~uint64_t(0);
  uint64_t total = 0;
  for (uint32_t i = 0; i < n; ++i) total += pg.datas[size_t(rel) + i].size();
  return total;
}

int plog_read_range(void* h, uint32_t g, uint64_t start, uint32_t n,
                    uint8_t* blob_out, uint32_t* lens_out,
                    uint64_t* terms_out) {
  Plog* p = static_cast<Plog*>(h);
  std::lock_guard<std::mutex> lk(p->mu);
  PlogGroup& pg = p->groups[g];
  int64_t rel = int64_t(start) - 1 - int64_t(pg.start);
  if (rel < 0 || size_t(rel) + n > pg.datas.size()) return -1;
  size_t off = 0;
  for (uint32_t i = 0; i < n; ++i) {
    const std::string& d = pg.datas[size_t(rel) + i];
    if (blob_out) std::memcpy(blob_out + off, d.data(), d.size());
    if (lens_out) lens_out[i] = uint32_t(d.size());
    if (terms_out) terms_out[i] = pg.terms[size_t(rel) + i];
    off += d.size();
  }
  return 0;
}

// Batched multi-group read (the publish hot path): total bytes of all
// ranges, then one fill of concatenated payloads + per-entry lens in
// range order.  Returns UINT64_MAX / -1 if any range is unavailable.
uint64_t plog_ranges_bytes(void* h, uint32_t n_ranges,
                           const uint32_t* groups, const uint64_t* starts,
                           const uint32_t* counts) {
  Plog* p = static_cast<Plog*>(h);
  std::lock_guard<std::mutex> lk(p->mu);
  uint64_t total = 0;
  for (uint32_t r = 0; r < n_ranges; ++r) {
    PlogGroup& pg = p->groups[groups[r]];
    int64_t rel = int64_t(starts[r]) - 1 - int64_t(pg.start);
    if (rel < 0 || size_t(rel) + counts[r] > pg.datas.size())
      return ~uint64_t(0);
    for (uint32_t i = 0; i < counts[r]; ++i)
      total += pg.datas[size_t(rel) + i].size();
  }
  return total;
}

int plog_read_groups(void* h, uint32_t n_ranges, const uint32_t* groups,
                     const uint64_t* starts, const uint32_t* counts,
                     uint8_t* blob_out, uint32_t* lens_out) {
  Plog* p = static_cast<Plog*>(h);
  std::lock_guard<std::mutex> lk(p->mu);
  size_t off = 0, li = 0;
  for (uint32_t r = 0; r < n_ranges; ++r) {
    PlogGroup& pg = p->groups[groups[r]];
    int64_t rel = int64_t(starts[r]) - 1 - int64_t(pg.start);
    if (rel < 0 || size_t(rel) + counts[r] > pg.datas.size()) return -1;
    for (uint32_t i = 0; i < counts[r]; ++i) {
      const std::string& d = pg.datas[size_t(rel) + i];
      std::memcpy(blob_out + off, d.data(), d.size());
      lens_out[li++] = uint32_t(d.size());
      off += d.size();
    }
  }
  return 0;
}

// Combined leader-append path: for each range i, write WAL ENTRY records
// AND the payload-log range, all entries of range i sharing terms[i].
// Ranges are (group, start, count) with payload bytes concatenated in
// `blob` / per-entry `lens` in range order.  One call per peer per tick.
int walplog_put_uniform(void* wal_h, void* plog_h, uint32_t n_ranges,
                        const uint32_t* groups, const uint64_t* starts,
                        const uint32_t* counts, const uint64_t* terms,
                        const uint8_t* blob, const uint32_t* lens) {
  Wal* w = static_cast<Wal*>(wal_h);
  Plog* p = static_cast<Plog*>(plog_h);
  std::lock_guard<std::mutex> lw(w->mu);
  std::lock_guard<std::mutex> lp(p->mu);
  size_t off = 0, li = 0;
  std::vector<uint64_t> tbuf;
  std::vector<uint8_t> body;
  for (uint32_t r = 0; r < n_ranges; ++r) {
    uint32_t n = counts[r];
    tbuf.assign(n, terms[r]);
    size_t range_bytes = 0;
    for (uint32_t i = 0; i < n; ++i) {
      wal_entry_locked(w, body, groups[r], starts[r] + i, terms[r],
                       blob + off + range_bytes, lens[li + i]);
      range_bytes += lens[li + i];
    }
    int rc = plog_put_locked(p->groups[groups[r]], starts[r], n,
                             tbuf.data(), blob + off, lens + li, -1);
    if (rc != 0) return rc;
    off += range_bytes;
    li += n;
  }
  return 0;
}

// Combined mirror path for the WHOLE cluster: phase A reads every
// source range into scratch (so a same-tick truncation or overwrite on
// any source cannot tear any mirror — the read-all-before-write-all
// contract); phase B writes each destination's payload-log range +
// truncation and its WAL ENTRY records.  `wals`/`plogs` are per-peer
// handle arrays; `peer`/`src` index them.
int walplog_mirror_all(void** wals, void** plogs, uint32_t n_mirrors,
                       const uint32_t* peer, const uint32_t* src,
                       const uint32_t* groups, const uint64_t* starts,
                       const uint32_t* counts, const int64_t* new_lens,
                       uint64_t* per_peer_bytes) {
  struct Scratch {
    std::vector<std::string> datas;
    std::vector<uint64_t> terms;
  };
  std::vector<Scratch> scratch(n_mirrors);
  for (uint32_t i = 0; i < n_mirrors; ++i) {
    Plog* sp = static_cast<Plog*>(plogs[src[i]]);
    std::lock_guard<std::mutex> lk(sp->mu);
    PlogGroup& pg = sp->groups[groups[i]];
    int64_t rel = int64_t(starts[i]) - 1 - int64_t(pg.start);
    uint32_t n = counts[i];
    if (n == 0) continue;
    if (rel < 0 || size_t(rel) + n > pg.datas.size()) return -1;
    scratch[i].datas.assign(pg.datas.begin() + rel,
                            pg.datas.begin() + rel + n);
    scratch[i].terms.assign(pg.terms.begin() + rel,
                            pg.terms.begin() + rel + n);
  }
  for (uint32_t i = 0; i < n_mirrors; ++i) {
    Wal* w = static_cast<Wal*>(wals[peer[i]]);
    Plog* dp = static_cast<Plog*>(plogs[peer[i]]);
    uint32_t n = counts[i];
    std::lock_guard<std::mutex> lw(w->mu);
    std::lock_guard<std::mutex> lp(dp->mu);
    PlogGroup& pg = dp->groups[groups[i]];
    int64_t rel = int64_t(starts[i]) - 1 - int64_t(pg.start);
    std::vector<uint8_t> body;
    size_t buf0 = w->buf.size();
    for (uint32_t k = 0; k < n; ++k) {
      const std::string& d = scratch[i].datas[k];
      wal_entry_locked(w, body, groups[i], starts[i] + k,
                       scratch[i].terms[k],
                       reinterpret_cast<const uint8_t*>(d.data()),
                       uint32_t(d.size()));
      int64_t pos = rel + int64_t(k);
      if (pos < 0) continue;
      if (pos < int64_t(pg.datas.size())) {
        pg.datas[size_t(pos)] = d;
        pg.terms[size_t(pos)] = scratch[i].terms[k];
      } else if (pos == int64_t(pg.datas.size())) {
        pg.datas.push_back(d);
        pg.terms.push_back(scratch[i].terms[k]);
      } else {
        return -1;
      }
    }
    // Framed-byte accounting from actual buffer growth (no layout
    // constant to drift from the Python struct definitions).
    if (per_peer_bytes) per_peer_bytes[peer[i]] += w->buf.size() - buf0;
    int64_t nl = new_lens[i];
    if (nl >= 0) {
      int64_t keep = nl - int64_t(pg.start);
      if (keep < 0) keep = 0;
      if (size_t(keep) < pg.datas.size()) {
        pg.datas.resize(size_t(keep));
        pg.terms.resize(size_t(keep));
      }
    }
  }
  return 0;
}

}  // extern "C"
