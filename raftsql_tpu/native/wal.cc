// Native WAL fast path — the C++ runtime piece of the storage layer.
//
// The reference's durability layer is vendored etcd/wal (Go) feeding an
// fsync before peer sends (reference raft.go:227-235).  At 100k groups per
// tick the record-framing CPU cost lands on the host hot loop, so the
// framing + CRC + buffered write path lives here; Python (storage/wal.py)
// keeps the cold paths (open/replay) and falls back to a pure-Python
// writer when this library is unavailable.
//
// Byte format is identical to storage/wal.py:
//   u32 crc32(body) | u32 body_len | body          (little endian)
//   body := u8 type | fields
//     type 1 ENTRY:     u32 group | u64 index | u64 term | bytes data
//     type 2 HARDSTATE: u32 group | u64 term  | i64 vote | u64 commit
//     type 3 SNAPSHOT:  u32 group | u64 index | u64 term
//     type 4 COMPACT:   u32 group | u64 index | u64 term
//
// Build: g++ -O2 -shared -fPIC -o _native_wal.so wal.cc
// ABI: plain C, consumed via ctypes (no pybind11 in this environment).

#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <mutex>
#include <unistd.h>
#include <vector>

namespace {

uint32_t kCrcTable[256];
struct CrcInit {
  CrcInit() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      kCrcTable[i] = c;
    }
  }
} crc_init;

uint32_t crc32z(const uint8_t* p, size_t n) {  // zlib-compatible
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; ++i) c = kCrcTable[(c ^ p[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

struct Wal {
  int fd = -1;
  std::vector<uint8_t> buf;  // framed records pending write+fsync
  std::mutex mu;
};

void put_u32(std::vector<uint8_t>& b, uint32_t v) {
  for (int i = 0; i < 4; ++i) b.push_back(uint8_t(v >> (8 * i)));
}
void put_u64(std::vector<uint8_t>& b, uint64_t v) {
  for (int i = 0; i < 8; ++i) b.push_back(uint8_t(v >> (8 * i)));
}

// Frame `body` (already assembled past the header) into w->buf.
void frame(Wal* w, const std::vector<uint8_t>& body) {
  put_u32(w->buf, crc32z(body.data(), body.size()));
  put_u32(w->buf, uint32_t(body.size()));
  w->buf.insert(w->buf.end(), body.begin(), body.end());
}

int flush_locked(Wal* w) {
  size_t off = 0;
  while (off < w->buf.size()) {
    ssize_t n = ::write(w->fd, w->buf.data() + off, w->buf.size() - off);
    if (n < 0) {
      if (errno == EINTR) continue;
      // Drop the consumed prefix so a retry/close cannot re-write bytes
      // already on disk (which would garble the tail with duplicates).
      w->buf.erase(w->buf.begin(), w->buf.begin() + off);
      return -1;
    }
    off += size_t(n);
  }
  w->buf.clear();
  return 0;
}

}  // namespace

extern "C" {

void* wal_open(const char* path) {
  int fd = ::open(path, O_WRONLY | O_CREAT | O_APPEND, 0644);
  if (fd < 0) return nullptr;
  Wal* w = new Wal();
  w->fd = fd;
  w->buf.reserve(1 << 20);
  return w;
}

int wal_append_entry(void* h, uint32_t group, uint64_t index, uint64_t term,
                     const uint8_t* data, uint32_t len) {
  Wal* w = static_cast<Wal*>(h);
  std::vector<uint8_t> body;
  body.reserve(21 + len);
  body.push_back(1);
  put_u32(body, group);
  put_u64(body, index);
  put_u64(body, term);
  if (len) body.insert(body.end(), data, data + len);
  std::lock_guard<std::mutex> lk(w->mu);
  frame(w, body);
  return 0;
}

// Batched append: n records whose data blobs are concatenated in `datas`
// with per-record lengths in `lens`.  One ctypes call per tick, not per
// record.
int wal_append_entries(void* h, uint32_t n, const uint32_t* groups,
                       const uint64_t* indexes, const uint64_t* terms,
                       const uint8_t* datas, const uint32_t* lens) {
  Wal* w = static_cast<Wal*>(h);
  std::lock_guard<std::mutex> lk(w->mu);
  size_t off = 0;
  std::vector<uint8_t> body;
  for (uint32_t i = 0; i < n; ++i) {
    body.clear();
    body.reserve(21 + lens[i]);
    body.push_back(1);
    put_u32(body, groups[i]);
    put_u64(body, indexes[i]);
    put_u64(body, terms[i]);
    if (lens[i]) body.insert(body.end(), datas + off, datas + off + lens[i]);
    off += lens[i];
    frame(w, body);
  }
  return 0;
}

int wal_set_snapshot(void* h, uint32_t group, uint64_t index,
                     uint64_t term) {
  Wal* w = static_cast<Wal*>(h);
  std::vector<uint8_t> body;
  body.reserve(21);
  body.push_back(3);
  put_u32(body, group);
  put_u64(body, index);
  put_u64(body, term);
  std::lock_guard<std::mutex> lk(w->mu);
  frame(w, body);
  return 0;
}

// Compaction floor marker (type 4): on replay, entries of `group` at or
// below `index` are dropped while the retained suffix SURVIVES — unlike
// the snapshot marker (type 3), which also clears the suffix because an
// installed state's history may conflict with it.
int wal_set_compact(void* h, uint32_t group, uint64_t index,
                    uint64_t term) {
  Wal* w = static_cast<Wal*>(h);
  std::vector<uint8_t> body;
  body.reserve(21);
  body.push_back(4);
  put_u32(body, group);
  put_u64(body, index);
  put_u64(body, term);
  std::lock_guard<std::mutex> lk(w->mu);
  frame(w, body);
  return 0;
}

int wal_set_hardstate(void* h, uint32_t group, uint64_t term, int64_t vote,
                      uint64_t commit) {
  Wal* w = static_cast<Wal*>(h);
  std::vector<uint8_t> body;
  body.reserve(29);
  body.push_back(2);
  put_u32(body, group);
  put_u64(body, term);
  put_u64(body, uint64_t(vote));
  put_u64(body, commit);
  std::lock_guard<std::mutex> lk(w->mu);
  frame(w, body);
  return 0;
}

// Batched hard states — one call per tick for every group whose
// (term, vote, commit) changed; under saturation that is ALL groups, so
// the per-record Python/ctypes round trip must not be per group.
int wal_set_hardstates(void* h, uint32_t n, const uint32_t* groups,
                       const uint64_t* terms, const int64_t* votes,
                       const uint64_t* commits) {
  Wal* w = static_cast<Wal*>(h);
  std::lock_guard<std::mutex> lk(w->mu);
  std::vector<uint8_t> body;
  for (uint32_t i = 0; i < n; ++i) {
    body.clear();
    body.reserve(29);
    body.push_back(2);
    put_u32(body, groups[i]);
    put_u64(body, terms[i]);
    put_u64(body, uint64_t(votes[i]));
    put_u64(body, commits[i]);
    frame(w, body);
  }
  return 0;
}

// Durable point: write all pending frames, then fdatasync.  Returns 0 on
// success, -1 on error (caller must treat as fatal — the ordering
// invariant is broken if we proceed).
int wal_sync(void* h) {
  Wal* w = static_cast<Wal*>(h);
  std::lock_guard<std::mutex> lk(w->mu);
  if (w->buf.empty()) return 0;
  if (flush_locked(w) != 0) return -1;
  return ::fdatasync(w->fd) == 0 ? 0 : -1;
}

int wal_close(void* h) {
  Wal* w = static_cast<Wal*>(h);
  int rc = 0;
  {
    std::lock_guard<std::mutex> lk(w->mu);
    if (!w->buf.empty() && flush_locked(w) == 0) ::fdatasync(w->fd);
    rc = ::close(w->fd);
  }
  delete w;
  return rc;
}

}  // extern "C"
