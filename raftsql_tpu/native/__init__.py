"""Native (C++) runtime components, loaded via ctypes.

The compute path is JAX/XLA; the host runtime's hot I/O paths are C++
(this package), mirroring how the reference leans on native code for its
storage engine (SQLite via cgo, reference db.go:6).  Everything here is
optional at runtime: each component has a pure-Python fallback so the
framework works on machines without a toolchain.
"""
from raftsql_tpu.native.build import load_native_wal  # noqa: F401
