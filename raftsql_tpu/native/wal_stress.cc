// Thread-sanitizer stress driver for the native WAL (SURVEY.md §5.2).
//
// Hammers one Wal handle from four threads with the same call mix the
// runtime produces concurrently: the tick thread's batched entry appends
// + hardstate + sync (runtime/node.py _wal_phase), the compactor's
// COMPACT markers (node.compact), and snapshot markers (InstallSnapshot).
// Built with -fsanitize=thread by `make tsan`; any data race in wal.cc's
// locking aborts the run.
//
// Usage: wal_stress <dir> [iters]

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
void* wal_open(const char* path);
int wal_append_entry(void*, uint32_t, uint64_t, uint64_t, const uint8_t*,
                     uint32_t);
int wal_append_entries(void*, uint32_t, const uint32_t*, const uint64_t*,
                       const uint64_t*, const uint8_t* const*,
                       const uint32_t*);
int wal_set_snapshot(void*, uint32_t, uint64_t, uint64_t);
int wal_set_compact(void*, uint32_t, uint64_t, uint64_t);
int wal_set_hardstate(void*, uint32_t, uint64_t, int64_t, uint64_t);
int wal_sync(void*);
int wal_close(void*);
}

int main(int argc, char** argv) {
  if (argc < 2) {
    std::fprintf(stderr, "usage: wal_stress <dir> [iters]\n");
    return 2;
  }
  std::string path = std::string(argv[1]) + "/wal-0.log";
  int iters = argc > 2 ? std::atoi(argv[2]) : 2000;
  void* w = wal_open(path.c_str());
  if (!w) {
    std::fprintf(stderr, "wal_open failed\n");
    return 1;
  }
  std::atomic<int> errs{0};
  const uint8_t payload[] = "SET k v";

  auto appender = [&](uint32_t group_base) {
    std::vector<uint32_t> groups(8);
    std::vector<uint64_t> idx(8), terms(8);
    std::vector<const uint8_t*> datas(8);
    std::vector<uint32_t> lens(8);
    for (int it = 0; it < iters; ++it) {
      for (int k = 0; k < 8; ++k) {
        groups[k] = group_base + (k % 4);
        idx[k] = uint64_t(it) * 8 + k + 1;
        terms[k] = it / 100 + 1;
        datas[k] = payload;
        lens[k] = sizeof(payload) - 1;
      }
      if (wal_append_entries(w, 8, groups.data(), idx.data(), terms.data(),
                             datas.data(), lens.data()))
        ++errs;
      if (wal_set_hardstate(w, group_base, it / 100 + 1, -1, it * 4)) ++errs;
      if (it % 16 == 0 && wal_sync(w)) ++errs;
    }
  };
  auto compactor = [&] {
    for (int it = 0; it < iters; ++it) {
      if (wal_set_compact(w, it % 8, it * 2 + 1, 1)) ++errs;
      if (it % 64 == 0 && wal_sync(w)) ++errs;
    }
  };
  auto snapshotter = [&] {
    for (int it = 0; it < iters; ++it) {
      if (wal_set_snapshot(w, it % 8, it * 4 + 1, 1)) ++errs;
    }
  };

  std::thread t1(appender, 0), t2(appender, 4), t3(compactor),
      t4(snapshotter);
  t1.join();
  t2.join();
  t3.join();
  t4.join();
  if (wal_sync(w)) ++errs;
  if (wal_close(w)) ++errs;
  if (errs.load()) {
    std::fprintf(stderr, "wal_stress: %d call failures\n", errs.load());
    return 1;
  }
  std::printf("wal_stress ok (%d iters x 4 threads)\n", iters);
  return 0;
}
