// HTTP load generator for the bench harness (the repo's `wrk`).
//
// The bench measures the SERVER; a Python load generator costs
// ~120-250us of interpreter time per request and, on a small host,
// competes with the server for the same cores — at 192 clients the
// Python clients alone saturate a core and the measurement reads as a
// server ceiling.  This is a single-thread epoll client: N keep-alive
// connections round-robin over the API ports, each looping
// PUT-INSERT -> 204/400, with per-request wall-clock latency recorded.
//
// Usage: http_load <seconds> <conns> <groups> <port> [port ...]
// Output: one JSON line on stdout:
//   {"n": completed, "errors": E, "p50_ms": .., "p99_ms": .., "secs": ..}
//
// Build: g++ -O2 -o _http_load http_load.cc   (native/build.py does this)

#include <algorithm>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <unistd.h>
#include <vector>

namespace {

double now_s() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return double(ts.tv_sec) + 1e-9 * double(ts.tv_nsec);
}

struct Conn {
  int fd = -1;
  uint32_t id = 0;
  uint64_t k = 0;          // per-conn request counter (unique bodies)
  bool writing = true;
  bool want_out = false;   // EPOLLOUT currently registered
  std::string out;         // request bytes pending write
  size_t off = 0;
  std::string in;          // response bytes so far
  double t0 = 0;
  bool done = false;
};

int g_groups = 1;
int g_ep = -1;

void set_mask(Conn& c, bool want_out) {
  if (want_out == c.want_out) return;
  c.want_out = want_out;
  epoll_event ev{};
  ev.events = EPOLLIN | (want_out ? EPOLLOUT : 0u);
  ev.data.u32 = c.id;
  epoll_ctl(g_ep, EPOLL_CTL_MOD, c.fd, &ev);
}

void build_request(Conn& c) {
  char body[96];
  int blen = snprintf(body, sizeof body,
                      "INSERT INTO t (v) VALUES ('n%u_%llu')", c.id,
                      (unsigned long long)c.k);
  uint64_t g = (c.id + c.k) % uint64_t(g_groups);
  char head[160];
  int hlen = snprintf(head, sizeof head,
                      "PUT / HTTP/1.1\r\nHost: b\r\nX-Raft-Group: %llu"
                      "\r\nContent-Length: %d\r\n\r\n",
                      (unsigned long long)g, blen);
  c.k++;
  c.out.assign(head, size_t(hlen));
  c.out.append(body, size_t(blen));
  c.off = 0;
  c.in.clear();
  c.writing = true;
  c.t0 = now_s();
}

// true = keep connection, false = caller must close (fatal send error).
bool pump_write(Conn& c) {
  while (c.off < c.out.size()) {
    ssize_t w = send(c.fd, c.out.data() + c.off, c.out.size() - c.off,
                     MSG_NOSIGNAL);
    if (w > 0) {
      c.off += size_t(w);
    } else if (w < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
      set_mask(c, true);
      return true;
    } else {
      return false;
    }
  }
  c.writing = false;
  set_mask(c, false);
  return true;
}

// Returns: 0 incomplete, 1 complete-success (204 — the Python client
// fallback's success criterion: a unique INSERT must commit; any other
// status is a failed request), -1 complete-failure or malformed.
int response_complete(const std::string& in) {
  size_t hend = in.find("\r\n\r\n");
  if (hend == std::string::npos) return 0;
  if (in.size() < 12 || in.compare(0, 9, "HTTP/1.1 ") != 0) return -1;
  int status = atoi(in.c_str() + 9);
  size_t clen = 0;
  size_t p = in.find("\r\n");       // minimal content-length scan
  while (p < hend) {
    size_t q = in.find("\r\n", p + 2);
    if (q == std::string::npos || q > hend) q = hend;
    if (q > p + 2) {
      std::string line = in.substr(p + 2, q - p - 2);
      for (auto& ch : line) ch = char(tolower(ch));
      if (line.rfind("content-length:", 0) == 0)
        clen = size_t(atoll(line.c_str() + 15));
    }
    p = q;
  }
  if (in.size() < hend + 4 + clen) return 0;
  return status == 204 ? 1 : -1;
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 5) {
    fprintf(stderr, "usage: %s seconds conns groups port...\n", argv[0]);
    return 2;
  }
  double seconds = atof(argv[1]);
  int conns = atoi(argv[2]);
  g_groups = atoi(argv[3]);
  std::vector<int> ports;
  for (int i = 4; i < argc; ++i) ports.push_back(atoi(argv[i]));

  g_ep = epoll_create1(0);
  std::vector<Conn> cs(static_cast<size_t>(conns));
  std::vector<double> lats;
  lats.reserve(1 << 20);
  uint64_t errors = 0;
  int live = conns;

  auto drop = [&](Conn& c) {
    ++errors;
    c.done = true;
    --live;
    close(c.fd);
  };

  for (int i = 0; i < conns; ++i) {
    Conn& c = cs[size_t(i)];
    c.id = uint32_t(i);
    c.fd = socket(AF_INET, SOCK_STREAM, 0);
    sockaddr_in a{};
    a.sin_family = AF_INET;
    a.sin_port = htons(uint16_t(ports[size_t(i) % ports.size()]));
    a.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
    if (connect(c.fd, reinterpret_cast<sockaddr*>(&a), sizeof a) != 0) {
      fprintf(stderr, "connect: %s\n", strerror(errno));
      return 3;
    }
    int one = 1;
    setsockopt(c.fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
    fcntl(c.fd, F_SETFL, fcntl(c.fd, F_GETFL, 0) | O_NONBLOCK);
    epoll_event ev{};
    ev.events = EPOLLIN;       // EPOLLOUT only while a write is pending
    ev.data.u32 = uint32_t(i);
    epoll_ctl(g_ep, EPOLL_CTL_ADD, c.fd, &ev);
  }

  double start = now_s(), stop_at = start + seconds;
  for (auto& c : cs) {
    build_request(c);
    if (!pump_write(c)) drop(c);
  }

  epoll_event evs[256];
  while (live > 0) {
    int n = epoll_wait(g_ep, evs, 256, 200);
    double now = now_s();
    if (now > stop_at + 5.0) break;      // drain cap for stragglers
    if (n == 0 && now > stop_at) break;  // idle past the deadline
    for (int e = 0; e < n; ++e) {
      Conn& c = cs[evs[e].data.u32];
      if (c.done) continue;
      if (c.writing) {
        if (!pump_write(c)) {
          drop(c);
          continue;
        }
        if (c.writing) continue;         // still pending; wait EPOLLOUT
      }
      if (!(evs[e].events & EPOLLIN)) continue;
      char buf[8192];
      for (;;) {
        ssize_t r = recv(c.fd, buf, sizeof buf, 0);
        if (r > 0) {
          c.in.append(buf, size_t(r));
          int st = response_complete(c.in);
          if (st == 0) continue;
          if (st == 1) {
            lats.push_back(now_s() - c.t0);
          } else {
            ++errors;
          }
          if (now_s() < stop_at) {
            build_request(c);
            if (!pump_write(c)) drop(c);
          } else {
            c.done = true;
            --live;
            close(c.fd);
          }
          break;
        } else if (r < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
          break;
        } else {                        // closed or hard error
          drop(c);
          break;
        }
      }
    }
  }

  double secs = now_s() - start;
  std::sort(lats.begin(), lats.end());
  auto pct = [&](double p) {
    if (lats.empty()) return 0.0;
    size_t i = size_t(p * double(lats.size() - 1));
    return lats[i] * 1e3;
  };
  printf("{\"n\": %zu, \"errors\": %llu, \"p50_ms\": %.3f, "
         "\"p99_ms\": %.3f, \"secs\": %.3f}\n",
         lats.size(), (unsigned long long)errors, pct(0.5), pct(0.99),
         secs);
  return 0;
}
