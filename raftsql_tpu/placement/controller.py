"""The placement controller: EWMA traffic in, leadership transfers out.

One pass (`evaluate()`, driven by a daemon thread at `interval_s`):

  1. Snapshot the per-group EWMA propose rates (GroupTraffic) and the
     current leader hints.  Groups whose leader is unknown are skipped —
     an election is already in progress and moving leadership would
     only add churn.
  2. Partition groups into balance DOMAINS: one per mesh group shard
     when the runtime shards groups (`_group_shard_of`), else one
     global domain.  Leadership can only move between peers, never
     between shards (a group's shard is a static device layout), so
     each shard's peer spread is balanced independently.
  3. In each domain, compute per-peer load = sum of rates of the groups
     that peer leads.  When the hottest peer carries more than
     `imbalance` times the coldest (+ the `min_rate` floor so an idle
     cluster never churns), pick the hottest group on the hot peer
     whose move IMPROVES the spread (rate ≤ half the gap — guards
     against ping-pong) and issue one transfer toward the coldest
     peer.
  4. Refused/failed transfers back off exponentially per group
     (`backoff_s` doubling to `backoff_cap_s`), so a learner-only
     target or a group mid-election cannot be hammered.

At most one transfer is issued per pass per domain; the engine's own
one-in-flight-per-group latch bounds concurrency below that.  The
controller never touches device state — it only calls the engine's
transfer_leadership, which validates and arms on the tick thread.
"""
from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

log = logging.getLogger("raftsql_tpu.placement")


class PlacementController:
    """Balance leadership of hot groups across peers.

    `node` is any engine exposing `traffic` (GroupTraffic),
    `leader_of(g)`, `transfer_leadership(g, target)`, and (optionally)
    `_group_shard_of(g)` + `transfers_doc()` — i.e. the fused/mesh
    host plane, or a RaftNode when an external feed stamps its traffic.
    """

    def __init__(self, node, interval_s: float = 0.5,
                 imbalance: float = 2.0, min_rate: float = 1.0,
                 backoff_s: float = 2.0, backoff_cap_s: float = 30.0,
                 log_cap: int = 128):
        self.node = node
        self.interval_s = float(interval_s)
        self.imbalance = float(imbalance)
        self.min_rate = float(min_rate)
        self.backoff_s = float(backoff_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.decisions: deque = deque(maxlen=log_cap)
        self.issued = 0
        self.refused = 0
        self.last_imbalance = 0.0
        # Per-group retry state: group -> (not-before monotonic time,
        # current backoff seconds).
        self._backoff: Dict[int, tuple] = {}
        # Elastic-keyspace plane (raftsql_tpu/reshard/plane.py),
        # attached by the server when both --placement and --reshard
        # are on: enables the split-hottest / merge-coldest verbs.
        self.reshard = None
        self.reshard_issued = 0
        self.reshard_refused = 0
        self._seen_outcome_tick = -1
        self._mu = threading.Lock()
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="placement")
        self._thread.start()

    def stop(self) -> None:
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self) -> None:
        while not self._stop_evt.wait(self.interval_s):
            try:
                self.evaluate()
            except Exception:                       # noqa: BLE001
                # The controller is an optimizer, never a liveness
                # dependency: a failed pass logs and the next runs.
                log.exception("placement pass failed")

    # -- one balancing pass ---------------------------------------------

    def _domains(self, G: int) -> Dict[int, List[int]]:
        shard_of = getattr(self.node, "_group_shard_of", None)
        if not callable(shard_of):
            return {0: list(range(G))}
        out: Dict[int, List[int]] = {}
        for g in range(G):
            out.setdefault(int(shard_of(g)), []).append(g)
        return out

    def _absorb_outcomes(self) -> None:
        """Stamp finished transfers' outcome + stall ticks back onto
        the issued decisions (flight-bundle attribution)."""
        fn = getattr(self.node, "transfers_doc", None)
        if fn is None:
            return
        for ev in fn().get("recent", ()):
            t = int(ev.get("tick", -1))
            if t <= self._seen_outcome_tick:
                continue
            for d in reversed(self.decisions):
                if (d["group"] == ev["group"] and d["to"] == ev["to"]
                        and d["outcome"] == "pending"):
                    d["outcome"] = ev["outcome"]
                    d["stall_ticks"] = ev.get("stall_ticks")
                    break
            self._seen_outcome_tick = max(self._seen_outcome_tick, t)

    def evaluate(self) -> Optional[dict]:
        """One balancing pass; returns the decision issued (or None).
        Thread-safe against concurrent passes (tests may drive it
        directly while the thread runs)."""
        with self._mu:
            return self._evaluate_locked()

    def _evaluate_locked(self) -> Optional[dict]:
        node = self.node
        traffic = getattr(node, "traffic", None)
        if traffic is None:
            return None
        self._absorb_outcomes()
        with traffic._mu:
            traffic._advance_rates_locked()
            rates = traffic._rate_p.copy()
        G = traffic.num_groups
        P = node.cfg.num_peers
        leaders = np.array([int(node.leader_of(g)) for g in range(G)])
        now = time.monotonic()
        decision = None
        pass_gap = 0.0
        for dom, groups in self._domains(G).items():
            loads = np.zeros(P)
            for g in groups:
                if leaders[g] >= 0:
                    loads[leaders[g]] += rates[g]
            hot_p = int(np.argmax(loads))
            sel = loads
            wit = getattr(node.cfg, "witness_set", frozenset())
            if wit:
                # A witness never leads, so it is always the idlest
                # slot — and never a legal transfer destination
                # (transfer_leadership would refuse it anyway; don't
                # even nominate it, or every pass burns a refusal).
                sel = loads.copy()
                sel[sorted(wit)] = np.inf
            cold_p = int(np.argmin(sel))
            if not np.isfinite(sel[cold_p]):
                continue            # every non-hot slot is a witness
            gap = loads[hot_p] - loads[cold_p]
            pass_gap = max(pass_gap, float(gap))
            if loads[hot_p] < self.min_rate \
                    or loads[hot_p] < self.imbalance * max(
                        loads[cold_p], self.min_rate / self.imbalance):
                continue
            # Hottest movable group on the hot peer whose rate fits
            # inside half the gap (the move must shrink the spread).
            cand = sorted((g for g in groups if leaders[g] == hot_p
                           and rates[g] > 0),
                          key=lambda g: -rates[g])
            for g in cand:
                nb = self._backoff.get(g)
                if nb is not None and now < nb[0]:
                    continue
                if rates[g] > gap / 2 + 1e-9:
                    continue
                decision = self._issue(g, hot_p, cold_p,
                                       float(rates[g]))
                break
            if decision is not None:
                break           # one transfer per pass
        self.last_imbalance = pass_gap
        return decision

    def _issue(self, g: int, frm: int, to: int, rate: float) -> dict:
        d = {"group": int(g), "from": frm + 1, "to": to + 1,
             "rate": round(rate, 3), "outcome": "pending",
             "stall_ticks": None, "at": time.time()}
        try:
            self.node.transfer_leadership(g, to)
            self.issued += 1
            self._backoff.pop(g, None)
        except Exception as e:                      # noqa: BLE001
            # Refused (in-flight, learner target, leadership moved
            # under us): exponential per-group backoff, try others.
            self.refused += 1
            d["outcome"] = f"refused: {e}"
            prev = self._backoff.get(g)
            b = min(prev[1] * 2 if prev else self.backoff_s,
                    self.backoff_cap_s)
            self._backoff[g] = (time.monotonic() + b, b)
        self.decisions.append(d)
        return d

    # -- elastic-keyspace verbs (raftsql_tpu/reshard/) ------------------

    def _group_rates(self):
        """(rates ndarray, live group list) from the traffic EWMA and
        the reshard plane's keymap, or None without both planes."""
        traffic = getattr(self.node, "traffic", None)
        if traffic is None or self.reshard is None:
            return None
        with traffic._mu:
            traffic._advance_rates_locked()
            rates = traffic._rate_p.copy()
        live = sorted(self.reshard.keymap.live_groups())
        return rates, live

    def split_hottest(self) -> Optional[dict]:
        """Rebalance the KEYSPACE, not just leadership: carve half of
        the hottest group's hash slots out to the least-loaded group
        (preferring a retired group id, which re-enters service).
        Returns the enqueued verb doc, or None when nothing qualifies;
        refusals (verb in flight) count and return None."""
        got = self._group_rates()
        if got is None:
            return None
        rates, live = got
        km = self.reshard.keymap
        cand = [g for g in live if len(km.slots_of(g)) >= 2]
        if not cand:
            return None
        src = max(cand, key=lambda g: (float(rates[g]), -g))
        retired = sorted(km.retired)
        if retired:
            dst = retired[0]
        else:
            others = [g for g in live if g != src]
            if not others:
                return None
            dst = min(others, key=lambda g: (float(rates[g]), g))
        owned = km.slots_of(src)
        hits = getattr(self.reshard, "slot_hits", None)
        if hits and any(hits[s] for s in owned):
            # Traffic-weighted partition: halving by slot COUNT under a
            # skewed workload can hand the hot slots themselves to dst,
            # crowning it the new hottest group (the zipfian demo in
            # scripts/bench_reshard.py regresses exactly that way).
            # Greedy heaviest-first into the lighter of keep/move bins
            # splits the observed per-slot load instead; with >= 2
            # owned slots and a nonzero total both bins end non-empty.
            keep = [0, []]
            move = [0, []]
            for s in sorted(owned, key=lambda s: (-hits[s], s)):
                b = keep if keep[0] <= move[0] else move
                b[0] += hits[s]
                b[1].append(s)
            slots = move[1]
        else:
            # No per-slot signal (plane without counters, or a cold
            # group picked by the rate EWMA alone): halve by count.
            slots = owned[:len(owned) // 2]
        try:
            doc = self.reshard.enqueue("split", src, dst, slots)
            self.reshard_issued += 1
            return doc
        except Exception as e:                      # noqa: BLE001
            self.reshard_refused += 1
            log.info("split-hottest refused: %s", e)
            return None

    def merge_coldest(self) -> Optional[dict]:
        """Fold the coldest group's slots into the next-coldest live
        group and retire its id (shrink G under a fading keyspace)."""
        got = self._group_rates()
        if got is None:
            return None
        rates, live = got
        if len(live) < 2:
            return None
        src = min(live, key=lambda g: (float(rates[g]), g))
        rest = [g for g in live if g != src]
        dst = min(rest, key=lambda g: (float(rates[g]), g))
        try:
            doc = self.reshard.enqueue("merge", src, dst)
            self.reshard_issued += 1
            return doc
        except Exception as e:                      # noqa: BLE001
            self.reshard_refused += 1
            log.info("merge-coldest refused: %s", e)
            return None

    # -- exports --------------------------------------------------------

    def doc(self) -> dict:
        """Flight-bundle attachment: the recent decision log (group,
        from, to, outcome, stall ticks) plus issue counters."""
        with self._mu:
            self._absorb_outcomes()
            return {"issued": self.issued, "refused": self.refused,
                    "last_imbalance": round(self.last_imbalance, 3),
                    "decisions": [dict(d) for d in self.decisions]}

    def metrics_doc(self) -> dict:
        """Numeric gauges for /metrics (prom-renderable leaves only)."""
        return {"issued": self.issued, "refused": self.refused,
                "last_imbalance": round(self.last_imbalance, 3),
                "backoff_groups": len(self._backoff),
                "reshard_issued": self.reshard_issued,
                "reshard_refused": self.reshard_refused}
