"""Traffic-aware leadership placement (ROADMAP: placement item).

The PR-8 telemetry plane measures per-group traffic (utils/metrics.py
GroupTraffic EWMA rates); the PR-11 transfer plane can MOVE leadership
(thesis §3.10 TimeoutNow, runtime/hostplane.py / runtime/node.py
transfer_leadership).  This package closes the loop: a controller
thread that watches the traffic feed and issues graceful transfers to
balance hot groups across peers — and, on the mesh runtime, within
each group shard — with per-group retry/backoff and a recent-decision
log that flight bundles attach for attribution (obs/flight.py).
"""
from raftsql_tpu.placement.controller import PlacementController

__all__ = ["PlacementController"]
