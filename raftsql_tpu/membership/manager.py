"""MembershipManager — the host plane of dynamic membership.

One manager per runtime instance (RaftNode, or the whole fused
cluster).  It owns the APPLIED configuration per group, validates and
builds conf-change entries (transport/codec.py conf-entry kind), tracks
conf entries that are appended-but-uncommitted so the publish plane can
apply + scrub them by index without scanning payload bytes on the hot
path, and enforces the two-phase joint protocol:

    admin op        entry 1 (at commit)          entry 2 (auto, leader)
    add learner     LEARNER  (1-phase)           —
    promote/remove  ENTER_JOINT (C_old,new)      LEAVE_JOINT (C_new)

with at most ONE change in flight per group: a new change is refused
while a conf entry is pending or the group sits in a joint config (the
leader auto-proposes the LEAVE_JOINT; any leader — including one
elected mid-transition — finishes an open joint state, so a leader
crash between the two entries cannot wedge the group).

Masks are u64 slot bitmasks (bit p = peer slot p); P <= 64.

Threading: admin/API threads call make_change/describe/counts; the
runtime's tick thread calls note_appended/note_truncated/take_committed/
apply.  All config mutation happens under one lock; the tick-side lists
are tick-thread-only.
"""
from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from raftsql_tpu.transport.codec import (CONF_KIND_ENTER_JOINT,
                                         CONF_KIND_LEARNER,
                                         CONF_KIND_LEAVE_JOINT,
                                         decode_conf_entry,
                                         encode_conf_entry,
                                         is_conf_entry)


class MembershipError(ValueError):
    """An illegal membership change (unknown op, peer not a learner,
    change already in flight, would empty the voter set, ...)."""


class MembershipLagError(MembershipError):
    """Learner too far behind to promote safely; retry after catch-up
    (the leader's host catch-up / InstallSnapshot path is feeding it)."""


class NotLeaderForChange(MembershipError):
    """Membership changes are accepted at the group's leader only;
    retry at `leader` (1-based node id, 0 = unknown)."""

    def __init__(self, group: int, leader: int):
        super().__init__(
            f"group {group}: membership changes go to the leader"
            + (f"; leader is node {leader}" if leader > 0 else ""))
        self.group = group
        self.leader = leader


def popcount(mask: int) -> int:
    return bin(mask).count("1")


def mask_bits(mask: int, p: int) -> List[int]:
    return [i for i in range(p) if mask >> i & 1]


@dataclasses.dataclass
class GroupConfig:
    """The APPLIED configuration of one group.

    `joint == voters` in the stable state; while a joint change is in
    flight `joint` holds C_old and `voters` C_new (commit and election
    need a majority of both — ops/quorum.py).  `index` is the log index
    of the conf entry that produced this config (0 = boot default).
    """
    voters: int
    joint: int
    learners: int
    index: int = 0

    @property
    def is_joint(self) -> bool:
        return self.joint != self.voters

    def entry(self, kind: int) -> bytes:
        return encode_conf_entry(kind, self.voters, self.joint,
                                 self.learners)

    def describe(self, p: int) -> dict:
        return {
            "voters": mask_bits(self.voters, p),
            "joint_old_voters": (mask_bits(self.joint, p)
                                 if self.is_joint else None),
            "learners": mask_bits(self.learners, p),
            "joint": self.is_joint,
            "conf_index": self.index,
        }


class MembershipManager:
    def __init__(self, num_peers: int, num_groups: int,
                 initial_voters: Optional[Tuple[int, ...]] = None,
                 write_quorum: Optional[int] = None,
                 election_quorum: Optional[int] = None,
                 witnesses: Tuple[int, ...] = (),
                 unsafe_geometry: bool = False):
        if num_peers > 64:
            raise MembershipError(
                "membership masks are u64 slot bitmasks: P <= 64")
        self.P = num_peers
        self.G = num_groups
        # Quorum geometry (config.py flexible quorums): explicit sizes
        # apply only to FULL masks (ops/quorum.py mask_threshold
        # contract); reduced masks fall back to their own majority.
        self.write_quorum = write_quorum
        self.election_quorum = election_quorum
        self.witness_mask = 0
        for w in witnesses:
            self.witness_mask |= 1 << w
        self.unsafe_geometry = unsafe_geometry
        full = (1 << num_peers) - 1
        if initial_voters is not None:
            full = 0
            for v in initial_voters:
                full |= 1 << v
        self._boot_voters = full
        self._lock = threading.Lock()
        self._cfg: List[GroupConfig] = [
            GroupConfig(voters=full, joint=full, learners=0)
            for _ in range(num_groups)]
        # Tick-thread state: conf entries appended to the local log but
        # not yet committed, [(idx, data)] ascending per group.
        self._appended: List[List[Tuple[int, bytes]]] = [
            [] for _ in range(num_groups)]
        # One-in-flight latch per group: held from make_change until the
        # resulting entry APPLIES (or its log slot is truncated away).
        self._pending: List[Optional[str]] = [None] * num_groups
        # Leader-side LEAVE_JOINT pacing (re-propose after a quiet spell
        # so a lost/truncated proposal cannot wedge the transition).
        self._leave_tick: Dict[int, int] = {}
        self.joint_groups: set = set()
        self.conf_changes_applied = 0

    # -- introspection --------------------------------------------------

    def config(self, group: int) -> GroupConfig:
        with self._lock:
            return dataclasses.replace(self._cfg[group])

    def is_default(self, group: int) -> bool:
        c = self._cfg[group]
        return (c.index == 0 and not c.learners
                and c.voters == c.joint == (1 << self.P) - 1)

    def is_voter(self, group: int, peer: int) -> bool:
        c = self._cfg[group]
        return bool((c.voters | c.joint) >> peer & 1)

    def voter_mask(self, group: int) -> int:
        """voters|joint bitmask of the applied config (invariant
        checkers' view of who may hold leadership)."""
        with self._lock:
            c = self._cfg[group]
        return c.voters | c.joint

    def describe(self, group: int) -> dict:
        with self._lock:
            d = self._cfg[group].describe(self.P)
        d["pending"] = self._pending[group]
        return d

    def counts(self) -> Tuple[int, int]:
        """(total voter slots, total learner slots) across all groups —
        the /metrics members_voters / members_learners export."""
        with self._lock:
            v = sum(popcount(c.voters) for c in self._cfg)
            l = sum(popcount(c.learners) for c in self._cfg)
        return v, l

    def device_rows(self, group: int, self_id: int):
        """(voters_row [P] bool, joint_row [P] bool, self_is_voter) for
        core/state.py set_group_config."""
        with self._lock:
            c = self._cfg[group]
        vrow = np.zeros(self.P, bool)
        jrow = np.zeros(self.P, bool)
        for i in range(self.P):
            vrow[i] = bool(c.voters >> i & 1)
            jrow[i] = bool(c.joint >> i & 1)
        return vrow, jrow, bool((c.voters | c.joint) >> self_id & 1)

    def quorum_confirmed(self, group: int, ok: np.ndarray,
                         self_id: int) -> bool:
        """ReadIndex confirmation under the active config: `ok[p]` =
        peer p echoed a current-term round; self counts implicitly.
        Needs a majority of BOTH masks (joint)."""
        with self._lock:
            c = self._cfg[group]
        conf = ok.astype(bool).copy()
        if 0 <= self_id < self.P:
            conf[self_id] = True

        def maj(mask: int) -> bool:
            n = popcount(mask)
            got = sum(1 for i in range(self.P)
                      if mask >> i & 1 and conf[i])
            return got >= self._write_need(mask)
        return maj(c.voters) and maj(c.joint)

    def quorum_nth(self, group: int, vals: np.ndarray) -> int:
        """Mask-weighted quorum-th largest of per-peer values under the
        active config — the lease plane's "latest clock at which a full
        quorum had confirmed us" (runtime/node.py lease_read; vals[p]
        already carries the caller's self stamp).  Joint consensus
        takes the MIN of both masks' quorum values: a lease is only as
        fresh as the staler majority, exactly like the masked commit
        rule."""
        with self._lock:
            c = self._cfg[group]

        def nth(mask: int) -> int:
            got = sorted((int(vals[i]) for i in range(self.P)
                          if mask >> i & 1), reverse=True)
            if not got:
                return -(1 << 40)    # all-learner: no quorum, no lease
            return got[self._write_need(mask) - 1]
        return min(nth(c.voters), nth(c.joint))

    def _write_need(self, mask: int) -> int:
        """Write-quorum threshold for a voter mask: the explicit
        flexible size on a FULL mask, the mask's own majority otherwise
        (mask_threshold contract — an explicit size was validated
        against all P slots and carries no intersection guarantee over
        a subset)."""
        n = popcount(mask)
        if self.write_quorum is not None and n == self.P:
            return self.write_quorum
        return n // 2 + 1

    def _check_geometry(self, new_voters: int, old_voters: int) -> None:
        """Re-validate quorum geometry across both joint halves before
        a config change flies (config.py validated the boot geometry
        against all P slots; a change must not re-open the hole).  Each
        half's effective thresholds follow the full-mask contract, so
        the intersection invariants W+E > n and 2E > n must hold per
        half — and a half whose voters are all witnesses could never
        elect a leader or apply a command, so at least one non-witness
        voter must survive in both."""
        if not self.unsafe_geometry:
            for mask in (new_voters, old_voters):
                n = popcount(mask)
                full = n == self.P
                w = self.write_quorum if (
                    full and self.write_quorum is not None) else n // 2 + 1
                e = self.election_quorum if (
                    full and self.election_quorum is not None) else n // 2 + 1
                if w + e <= n or 2 * e <= n:
                    raise MembershipError(
                        f"change would yield non-intersecting quorum "
                        f"geometry (W={w}, E={e}, n={n})")
        for mask in (new_voters, old_voters):
            if mask and not mask & ~self.witness_mask:
                raise MembershipError(
                    "change would leave only witness voters (someone "
                    "has to lead and apply)")

    # -- building changes (admin plane) ---------------------------------

    OPS = ("add", "add_learner", "remove_learner", "promote", "remove")

    def make_change(self, group: int, op: str, peer: int) -> bytes:
        """Validate and build the conf entry for an admin op.  Raises
        MembershipError; never touches the applied config (that happens
        at commit, via apply())."""
        if not 0 <= peer < self.P:
            raise MembershipError(
                f"peer slot {peer} out of range [0, {self.P})")
        bit = 1 << peer
        with self._lock:
            c = self._cfg[group]
            if self._pending[group] is not None:
                raise MembershipError(
                    f"group {group}: a membership change is already in "
                    f"flight ({self._pending[group]}); one at a time")
            if c.is_joint:
                raise MembershipError(
                    f"group {group}: joint config transition still "
                    "completing; retry shortly")
            if op in ("add", "add_learner"):
                if c.voters & bit:
                    raise MembershipError(f"peer {peer} is already a voter")
                if c.learners & bit:
                    raise MembershipError(
                        f"peer {peer} is already a learner")
                entry = encode_conf_entry(
                    CONF_KIND_LEARNER, c.voters, c.voters,
                    c.learners | bit)
            elif op == "remove_learner":
                if not c.learners & bit:
                    raise MembershipError(f"peer {peer} is not a learner")
                entry = encode_conf_entry(
                    CONF_KIND_LEARNER, c.voters, c.voters,
                    c.learners & ~bit)
            elif op == "promote":
                if not c.learners & bit:
                    raise MembershipError(
                        f"peer {peer} is not a learner (add it first)")
                self._check_geometry(c.voters | bit, c.voters)
                entry = encode_conf_entry(
                    CONF_KIND_ENTER_JOINT, c.voters | bit, c.voters,
                    c.learners & ~bit)
            elif op == "remove":
                if not c.voters & bit:
                    raise MembershipError(f"peer {peer} is not a voter")
                if popcount(c.voters & ~bit) == 0:
                    raise MembershipError(
                        "refusing to remove the last voter")
                self._check_geometry(c.voters & ~bit, c.voters)
                entry = encode_conf_entry(
                    CONF_KIND_ENTER_JOINT, c.voters & ~bit, c.voters,
                    c.learners)
            else:
                raise MembershipError(
                    f"unknown membership op {op!r}; one of {self.OPS}")
            self._pending[group] = f"{op} peer {peer}"
        return entry

    def maybe_leave(self, group: int, tick_no: int,
                    cooldown: int) -> Optional[bytes]:
        """LEAVE_JOINT entry for a joint group, rate-limited: the
        group's leader calls this every tick; a proposal goes out at
        most once per `cooldown` ticks until the leave applies."""
        with self._lock:
            c = self._cfg[group]
            if not c.is_joint:
                return None
            last = self._leave_tick.get(group, -cooldown)
            if tick_no - last < cooldown:
                return None
            self._leave_tick[group] = tick_no
            return encode_conf_entry(CONF_KIND_LEAVE_JOINT, c.voters,
                                     c.voters, c.learners)

    # -- tick-thread plumbing -------------------------------------------

    def note_appended(self, group: int, idx: int, data: bytes) -> None:
        """A conf entry landed in the local log at `idx` (leader append
        or accepted follower append/catch-up)."""
        lst = self._appended[group]
        # A re-accepted duplicate (same idx) or an overwrite after
        # truncation replaces the stale record.
        lst[:] = [(i, d) for (i, d) in lst if i < idx]
        lst.append((idx, data))

    def note_truncated(self, group: int, start: int) -> None:
        """Conflict truncation from `start`: pending conf entries in
        the clobbered suffix never commit."""
        lst = self._appended[group]
        lst[:] = [(i, d) for (i, d) in lst if i < start]

    def take_committed(self, group: int, lo: int,
                       hi: int) -> List[Tuple[int, bytes]]:
        """Pop appended conf entries with lo < idx <= hi (they are
        committing now); ascending order."""
        lst = self._appended[group]
        if not lst:
            return []
        out = [(i, d) for (i, d) in lst if lo < i <= hi]
        if out:
            lst[:] = [(i, d) for (i, d) in lst if i > hi]
        return out

    def has_appended(self, group: int) -> bool:
        return bool(self._appended[group])

    def appended_list(self, group: int) -> List[Tuple[int, bytes]]:
        """Copy of the appended-but-uncommitted conf entries (the fused
        runtime merges per-peer restore views through this)."""
        return list(self._appended[group])

    def abort_pending(self, group: int) -> None:
        """Release the one-in-flight latch: the pending entry's log
        slot was conflict-truncated before commit (the change never
        happened) — a new admin op may be issued."""
        with self._lock:
            self._pending[group] = None

    # -- apply at commit ------------------------------------------------

    def apply(self, group: int, idx: int,
              data: bytes) -> Optional[GroupConfig]:
        """Apply a COMMITTED conf entry.  Full-picture entries make
        this an unconditional set, so re-delivery/replay is idempotent;
        entries at or below the applied baseline are stale and skipped.
        Returns the new config, or None if nothing changed."""
        got = decode_conf_entry(data)
        if got is None:
            return None
        kind, voters, joint, learners = got
        with self._lock:
            c = self._cfg[group]
            if idx <= c.index:
                return None
            if voters == 0:
                return None          # corrupt/hostile: keep a voter set
            new = GroupConfig(voters=voters, joint=joint,
                              learners=learners, index=idx)
            self._cfg[group] = new
            self._pending[group] = None
            if new.is_joint:
                self.joint_groups.add(group)
            else:
                self.joint_groups.discard(group)
                self._leave_tick.pop(group, None)
            self.conf_changes_applied += 1
            return dataclasses.replace(new)

    # -- restart / snapshot recovery ------------------------------------

    def restore(self, group: int,
                baseline: Optional[Tuple[int, int, int, int, int]],
                entries, start: int, commit: int) -> bool:
        """Rebuild the group's active config after a WAL replay.

        `baseline` is the replayed REC_CONF (or None); `entries` the
        replayed (term, data) list beginning at log index start+1, and
        `commit` the replayed commit index.  Conf entries committed
        above the baseline re-apply in order; appended-but-uncommitted
        ones re-enter the pending list so the live publish path applies
        them when they commit.  Returns True when the group ends in a
        non-default config (caller patches the device masks)."""
        if baseline is not None:
            idx, kind, voters, joint, learners = baseline
            with self._lock:
                self._cfg[group] = GroupConfig(
                    voters=voters, joint=joint, learners=learners,
                    index=idx)
                if self._cfg[group].is_joint:
                    self.joint_groups.add(group)
        for off, (_, data) in enumerate(entries):
            idx = start + 1 + off
            if not is_conf_entry(data):
                continue
            if idx <= commit:
                self.apply(group, idx, data)
            else:
                self.note_appended(group, idx, data)
        return not self.is_default(group)
