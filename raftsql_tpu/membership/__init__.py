"""Dynamic membership: voter masks, joint consensus, learner catch-up.

The reference inherits etcd/raft's ConfChange machinery; the batched
rebuild froze the peer set (static cfg.quorum).  This package is the
TPU-native redesign: the per-group configuration is DEVICE data — a
[G, P] voter bitmask plus a second mask while a joint C_old,new change
is in flight (core/state.py PeerState.voters / voters_joint) — read by
every quorum in the fused step (ops/quorum.py mask-weighted kernels),
so N groups can sit in N different configurations inside one dispatch.

Changes travel as marked log entries (transport/codec.py conf-entry
record kind), apply at commit in the two-phase joint style
(C_old,new -> C_new, one in flight per group), and are durably
baselined in the WAL (storage/wal.py REC_CONF).  Learner slots receive
AppendEntries/InstallSnapshot but stay outside every quorum until
caught up and promoted.  P is provisioned slot CAPACITY (a static
device shape): membership moves voter bits between slots; it never
resizes P.
"""
from raftsql_tpu.membership.manager import (GroupConfig, MembershipError,
                                            MembershipLagError,
                                            MembershipManager,
                                            NotLeaderForChange)

__all__ = ["GroupConfig", "MembershipError", "MembershipLagError",
           "MembershipManager", "NotLeaderForChange"]
