"""Windowed commit-index advance + on-device commit trajectories.

BASELINE.json config 4 ("100k groups, log-matching + per-group commit-index
prefix scan"): the reference's commit advance lives inside vendored
etcd/raft `maybeCommit`, driven once per Ready from the event loop
(reference raft.go:224-235).  Here it is a dense kernel over all groups:

  * `windowed_commit_index` — the full raft §5.3/§5.4.2 rule: advance to
    the LARGEST log position n with commit < n <= quorum-match whose entry
    term equals the leader's current term.  `ops.quorum.quorum_commit_index`
    checks only n = quorum-match (etcd's shortcut, correct but weaker when
    the quorum index sits on an old-term entry); the windowed form scans
    every in-window position at once as a masked max — O(W) lanes, no loop.

  * `running_commit` — an associative prefix scan (`lax.associative_scan`
    over `jnp.maximum`) turning per-tick commit candidates [T, G] into the
    monotone committed-index trajectory, entirely on device.  This is how
    the benchmark harness derives propose→commit latency percentiles
    without moving T x G arrays to the host.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

I32 = jnp.int32


def windowed_commit_index(match: jax.Array, log_term: jax.Array,
                          log_len: jax.Array, commit: jax.Array,
                          term: jax.Array, is_leader: jax.Array,
                          *, quorum: int, window: int) -> jax.Array:
    """[G, P] match + [G, W] term ring -> [G] advanced commit index.

    For every ring position w holding log index n (reconstructed from
    log_len, since position n lives at slot (n-1) % W and only the last W
    indexes are resident), n is committable iff:
      commit < n <= quorum_match  and  term_of(n) == current term.
    The advance is the max committable n, or `commit` unchanged.
    """
    P = match.shape[-1]
    sorted_match = jnp.sort(match, axis=-1)
    qmatch = sorted_match[..., P - quorum]                        # [G]
    return _windowed_from_qmatch(qmatch, log_term, log_len, commit,
                                 term, is_leader)


def masked_windowed_commit_index(match: jax.Array, log_term: jax.Array,
                                 log_len: jax.Array, commit: jax.Array,
                                 term: jax.Array, is_leader: jax.Array,
                                 *, voters: jax.Array,
                                 voters_joint: jax.Array,
                                 window: int, size=None) -> jax.Array:
    """The windowed rule under a per-group voter configuration
    (ops/quorum.py mask-weighted quorum): the scan's ceiling is the min
    of the two masks' quorum indexes (joint consensus), so every group
    can sit in a different configuration inside the one fused kernel.
    Full masks reproduce `windowed_commit_index` bit for bit; `size`
    applies the flexible write-quorum threshold on full masks."""
    from raftsql_tpu.ops.quorum import masked_quorum_match_index

    qmatch = jnp.minimum(
        masked_quorum_match_index(match, voters, size),
        masked_quorum_match_index(match, voters_joint, size))
    return _windowed_from_qmatch(qmatch, log_term, log_len, commit,
                                 term, is_leader)


def _windowed_from_qmatch(qmatch: jax.Array, log_term: jax.Array,
                          log_len: jax.Array, commit: jax.Array,
                          term: jax.Array,
                          is_leader: jax.Array) -> jax.Array:
    _, W = log_term.shape
    slot = jnp.arange(W, dtype=I32)[None, :]                      # [1, W]
    # Log index currently resident in each ring slot: the unique
    # n in (log_len - W, log_len] with (n-1) % W == slot.
    base = log_len[:, None] - 1                                   # [G, 1]
    n = base - (base - slot) % W + 1                              # [G, W]
    committable = (n > commit[:, None]) & (n <= qmatch[:, None]) \
        & (n >= 1) & (log_term == term[:, None])
    best = jnp.max(jnp.where(committable, n, 0), axis=-1)         # [G]
    ok = is_leader & (best > commit)
    return jnp.where(ok, best, commit)


def running_commit(candidates: jax.Array, axis: int = 0) -> jax.Array:
    """Monotone prefix-max over the tick axis: [T, ...] -> [T, ...].

    commit indexes never regress; given per-tick raw observations this
    yields the committed-index trajectory as one `associative_scan`.
    """
    return jax.lax.associative_scan(jnp.maximum, candidates, axis=axis)


def commit_latency_ticks(traj: jax.Array, targets: jax.Array) -> jax.Array:
    """First tick at which each target index is committed.

    traj: [T, G] monotone commit trajectory (from `running_commit`).
    targets: [G] log index per group (e.g. prop_base + n of a proposal).
    Returns [G] i32 tick of first commit >= target, or T if never.
    """
    T = traj.shape[0]
    hit = traj >= targets[None, :]                                # [T, G]
    first = jnp.argmax(hit, axis=0).astype(I32)
    return jnp.where(hit.any(axis=0), first, T)
