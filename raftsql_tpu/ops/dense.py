"""Backend-adaptive dense gather/scatter primitives for the hot tick.

XLA lowers `gather`/`scatter` with data-dependent indices to serialized
per-element updates on TPU, and the consensus step (core/step.py) is built
almost entirely of small ring reads/writes with such indices: profiled
through the single-chip TPU path, each gather/scatter HLO costs ~2 ms while
the equivalent mask-select-reduce costs ~1 µs (the step carried 12 gathers
+ 4 scatters ≈ 50 ms/tick).  The replacement formulation is TPU-first:

  read:   out[..., x] = Σ_w  where(idx[..., x] == w, src[..., w], 0)
  write:  dst[..., w] = where(hit[..., w], val[..., w], dst[..., w])

i.e. one-hot comparisons fused by XLA into elementwise+reduce — no
serialization, no dynamic indexing.  On CPU the native gather IS the fast
path (vectorized memcpy-like), so `take_last` picks per backend at trace
time; `RAFTSQL_DENSE=0/1` overrides it (tests/test_ops.py runs the core
equivalence checks on both paths).

The election jitter here replaces `jax.random.fold_in`+`randint` (threefry
is ~40 xor/shift/mul HLOs per tick, ~2 ms through the same path) with a
splitmix-style integer hash: deterministic in (key, tick, global group id),
uniform over the timeout span, and a handful of elementwise uint32 ops.

This module replaces nothing in the reference — it is the TPU-native cost
model asserting itself where etcd/raft (reference raft.go:30) used ordinary
pointer-chasing Go.
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp

U32 = jnp.uint32


def use_dense() -> bool:
    """Trace-time choice: one-hot dense ops (TPU) vs native gather (CPU)."""
    ov = os.environ.get("RAFTSQL_DENSE")
    if ov is not None:
        return ov == "1"
    return jax.default_backend() != "cpu"


def onehot_take(x: jax.Array, idx: jax.Array) -> jax.Array:
    """out[..., i] = x[..., idx[..., i]] as a one-hot select-reduce.

    x: [..., W]; idx: [..., X] int in [0, W) — out-of-range indices
    contribute 0.  The shared core of every dense read below.
    """
    W = x.shape[-1]
    hit = idx[..., None] == jnp.arange(W, dtype=idx.dtype)      # [..., X, W]
    return jnp.sum(jnp.where(hit, x[..., None, :], 0), axis=-1)


def take_last(x: jax.Array, idx: jax.Array) -> jax.Array:
    """`take_along_axis(x, idx, axis=-1)`, gather-free on TPU.

    x: [..., W]; idx: [..., X] int32 in [0, W) -> [..., X].
    """
    if not use_dense():
        return jnp.take_along_axis(x, idx, axis=-1)
    return onehot_take(x, idx)


def pick_peer(x: jax.Array, src: jax.Array) -> jax.Array:
    """x[g, src[g]] for x of shape [G, P, ...] — one-hot over the small P
    axis on every backend (P is 3-5; a gather would serialize G rows on
    TPU while the select-reduce is a handful of fused lanes).  Trailing
    message dims keep onehot_take from applying directly."""
    G, P = x.shape[0], x.shape[1]
    sel = jnp.arange(P, dtype=src.dtype)[None, :] == src[:, None]   # [G, P]
    m = sel.reshape((G, P) + (1,) * (x.ndim - 2))
    return jnp.sum(jnp.where(m, x, 0), axis=1)


def pick_batch(vals: jax.Array, idx: jax.Array) -> jax.Array:
    """vals[g, idx[g]] for vals of shape [G, E] — one-hot over the small E
    axis (same rationale as pick_peer)."""
    return onehot_take(vals, idx[:, None])[:, 0]


def ring_gather_values(vals: jax.Array, rel: jax.Array, n: jax.Array
                       ) -> jax.Array:
    """Per-slot batch values for a ring write: out[g, w] = vals[g, rel[g, w]]
    where rel[g, w] < n[g], else 0.

    vals: [G, E]; rel: [G, W] int32; n: [G] (clamped to E by the caller).
    """
    E = vals.shape[-1]
    live = rel < n[:, None]                                     # [G, W]
    if not use_dense():
        got = jnp.take_along_axis(vals, jnp.minimum(rel, E - 1), axis=-1)
        return jnp.where(live, got, 0)
    return jnp.where(live, onehot_take(vals, rel), 0)


def election_jitter(key_data: jax.Array, tick: jax.Array, gids: jax.Array,
                    lo: int, hi: int) -> jax.Array:
    """Per-group timeout draw in [lo, hi) — splitmix32-style finalizer over
    (key, tick, global group id).  Matches the contract of the
    fold_in+randint draw it replaces (core/step.py Phase 8): deterministic
    per (seed, peer, tick, GLOBAL gid), so mesh-sharded peers draw
    bit-identical jitter to the single-chip run.
    """
    kd = key_data.reshape(-1).astype(U32)
    x = (gids.astype(U32) * U32(0x9E3779B1)
         ^ tick.astype(U32) * U32(0x85EBCA77)
         ^ kd[0] * U32(0xC2B2AE3D) ^ kd[-1])
    x = (x ^ (x >> 16)) * U32(0x7FEB352D)
    x = (x ^ (x >> 15)) * U32(0x846CA68B)
    x = x ^ (x >> 16)
    span = max(hi - lo, 1)
    return (U32(lo) + x % U32(span)).astype(jnp.int32)


def key_data_of(rng: jax.Array) -> jax.Array:
    """Raw uint32 words of a PRNG key, old-style ([2] uint32) or typed."""
    if jnp.issubdtype(rng.dtype, jax.dtypes.prng_key):
        return jax.random.key_data(rng)
    return rng
