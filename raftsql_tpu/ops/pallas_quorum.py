"""Hand-written Pallas TPU kernel for the quorum commit reduction.

The quorum/commit advance (`ops.quorum.quorum_commit_index`) is the hot
reduction of the batched consensus step — the math of vendored etcd/raft's
`maybeCommit` (driven from the reference's event loop, raft.go:224-235)
over ALL groups at once.  XLA's fused sort+gather handles it well at small
P; this kernel removes the sort entirely:

  q-th largest of P match values == max_i { match[i] : #{j : match[j] >=
  match[i]} >= quorum }

which is an O(P^2) comparison network — P static VPU passes over a [Gb, P]
block, no data movement.  The entry-term lookup is a one-hot reduction over
the ring axis instead of a gather (gathers are the thing to avoid on the
VPU; a masked sum over W lanes fuses).

Blocks stream G in `block_g`-row tiles through VMEM; all shapes static.
On non-TPU backends the kernel runs in interpreter mode (slow, but keeps
tests hermetic on the CPU CI platform).

MEASURED VERDICT (round-5 rules race, live chip — bench_logs/
r5_tpu_head_e932a09.log): `point` beats this kernel at every benched
shape — 287.8M vs 78.8M commits/s at G=10k/P=3, and at its claimed
large-P regime (G=2k/P=15) point did 45.1M while THIS KERNEL'S COMPILE
HUNG past the bench timeout.  The sort XLA emits for the point rule
fuses into the surrounding step; this kernel's VMEM streaming does not.
`commit_rule="point"` stays the default at every P; the kernel is kept
as a tested reference implementation of the comparison-network idea and
as the repo's pallas exemplar, not as a fast path.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

I32 = jnp.int32


def _kernel(quorum: int, window: int,
            match_ref, log_term_ref, log_len_ref, commit_ref, term_ref,
            leader_ref, out_ref):
    match = match_ref[:]                      # [Gb, P]
    ring = log_term_ref[:]                    # [Gb, W]
    log_len = log_len_ref[:]                  # [Gb, 1]
    commit = commit_ref[:]                    # [Gb, 1]
    term = term_ref[:]                        # [Gb, 1]
    is_leader = leader_ref[:] != 0            # [Gb, 1]
    P = match.shape[-1]

    # q-th largest via the comparison network (static P-pass loop).
    cand = jnp.zeros_like(commit)             # [Gb, 1]
    for i in range(P):
        mi = match[:, i:i + 1]                # [Gb, 1]
        cnt = jnp.sum((match >= mi).astype(I32), axis=-1, keepdims=True)
        cand = jnp.where((cnt >= quorum) & (mi > cand), mi, cand)

    # term_of(cand) without a gather: one-hot over the ring axis.
    slot = (cand - 1) % window                # [Gb, 1]
    lanes = jax.lax.broadcasted_iota(I32, ring.shape, 1)
    cand_term = jnp.sum(jnp.where(lanes == slot, ring, 0), axis=-1,
                        keepdims=True)
    valid = (cand >= 1) & (cand <= log_len)
    cand_term = jnp.where(valid, cand_term, 0)

    ok = is_leader & (cand_term == term) & (cand > commit)
    out_ref[:] = jnp.where(ok, cand, commit)


def pallas_quorum_commit_index(match: jax.Array, log_term: jax.Array,
                               log_len: jax.Array, commit: jax.Array,
                               term: jax.Array, is_leader: jax.Array,
                               *, quorum: int, window: int,
                               block_g: int = 1024,
                               interpret: bool | None = None) -> jax.Array:
    """Drop-in replacement for `ops.quorum.quorum_commit_index`."""
    G, P = match.shape
    if interpret is None:
        # "axon" is the remote-TPU PJRT tunnel — compile for it too, or
        # the "hand-written TPU kernel" silently interprets on the very
        # hardware it was written for.
        interpret = jax.default_backend() not in ("tpu", "axon")
    gb = min(block_g, G)
    pad = (-G) % gb
    col = lambda x: x.astype(I32).reshape(G, 1)
    args = (match.astype(I32), log_term.astype(I32), col(log_len),
            col(commit), col(term), col(is_leader))
    if pad:
        args = tuple(jnp.pad(x, ((0, pad), (0, 0))) for x in args)
    gp = G + pad

    widths = (P, window, 1, 1, 1, 1)
    out = pl.pallas_call(
        functools.partial(_kernel, quorum, window),
        grid=(gp // gb,),
        in_specs=[pl.BlockSpec((gb, w), lambda i: (i, 0)) for w in widths],
        out_specs=pl.BlockSpec((gb, 1), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((gp, 1), I32),
        interpret=interpret,
    )(*args)
    return out[:G, 0]


# ---------------------------------------------------------------------------
# Mask-weighted variant (dynamic membership, raftsql_tpu/membership/):
# the static quorum constant becomes per-group [G, P] voter masks (plus
# the second joint-consensus mask), still one comparison network — the
# count just multiplies by the mask and the threshold is a per-row
# popcount majority.  With full masks this reproduces the static kernel
# exactly (tests/test_membership.py property-tests both paths).

_NEG = -(1 << 30)


def _masked_kernel(window: int, size,
                   match_ref, vot_ref, jvot_ref, log_term_ref,
                   log_len_ref, commit_ref, term_ref, leader_ref,
                   out_ref):
    match = match_ref[:]                      # [Gb, P]
    vot = vot_ref[:] != 0                     # [Gb, P]
    jvot = jvot_ref[:] != 0                   # [Gb, P]
    ring = log_term_ref[:]                    # [Gb, W]
    log_len = log_len_ref[:]                  # [Gb, 1]
    commit = commit_ref[:]                    # [Gb, 1]
    term = term_ref[:]                        # [Gb, 1]
    is_leader = leader_ref[:] != 0            # [Gb, 1]
    P = match.shape[-1]

    def qidx(mask):
        m = jnp.where(mask, match, _NEG)
        mi32 = mask.astype(I32)
        nv = jnp.sum(mi32, axis=-1, keepdims=True)      # [Gb, 1]
        need = nv // 2 + 1
        if size is not None:
            # Flexible write quorum on FULL masks only (mask_threshold
            # contract, ops/quorum.py): reduced masks keep majority.
            need = jnp.where(nv == P, I32(size), need)
        cand = jnp.full_like(commit, _NEG)
        for i in range(P):
            mi = m[:, i:i + 1]
            cnt = jnp.sum((m >= mi).astype(I32) * mi32, axis=-1,
                          keepdims=True)
            ok = mask[:, i:i + 1] & (cnt >= need) & (mi > cand)
            cand = jnp.where(ok, mi, cand)
        # Empty mask (all-learner group): no quorum index exists.
        return jnp.where(nv > 0, jnp.maximum(cand, 0), 0)

    # Joint consensus: the candidate must hold on BOTH masks.
    cand = jnp.minimum(qidx(vot), qidx(jvot))

    slot = (cand - 1) % window                # [Gb, 1]
    lanes = jax.lax.broadcasted_iota(I32, ring.shape, 1)
    cand_term = jnp.sum(jnp.where(lanes == slot, ring, 0), axis=-1,
                        keepdims=True)
    valid = (cand >= 1) & (cand <= log_len)
    cand_term = jnp.where(valid, cand_term, 0)

    ok = is_leader & (cand_term == term) & (cand > commit)
    out_ref[:] = jnp.where(ok, cand, commit)


def pallas_masked_quorum_commit_index(
        match: jax.Array, log_term: jax.Array, log_len: jax.Array,
        commit: jax.Array, term: jax.Array, is_leader: jax.Array,
        *, voters: jax.Array, voters_joint: jax.Array, window: int,
        size=None, block_g: int = 1024,
        interpret: bool | None = None) -> jax.Array:
    """Mask-weighted drop-in for `ops.quorum.masked_quorum_commit_index`."""
    G, P = match.shape
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")
    gb = min(block_g, G)
    pad = (-G) % gb
    col = lambda x: x.astype(I32).reshape(G, 1)
    args = (match.astype(I32), voters.astype(I32),
            voters_joint.astype(I32), log_term.astype(I32),
            col(log_len), col(commit), col(term), col(is_leader))
    if pad:
        args = tuple(jnp.pad(x, ((0, pad), (0, 0))) for x in args)
    gp = G + pad

    widths = (P, P, P, window, 1, 1, 1, 1)
    out = pl.pallas_call(
        functools.partial(_masked_kernel, window, size),
        grid=(gp // gb,),
        in_specs=[pl.BlockSpec((gb, w), lambda i: (i, 0)) for w in widths],
        out_specs=pl.BlockSpec((gb, 1), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((gp, 1), I32),
        interpret=interpret,
    )(*args)
    return out[:G, 0]
