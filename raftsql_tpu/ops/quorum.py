"""Quorum / commit-index kernels.

This is the math at the heart of the reference's vendored consensus library
(etcd/raft's `maybeCommit`, driven from the reference's event loop at
raft.go:224-235), recast as vectorized reductions over the `[G, P]`
match-index matrix:

  commit'[g] = the largest index replicated on a quorum of peers, provided
               the entry at that index carries the leader's current term
               (raft §5.4.2 — leaders only commit entries of their own term).

The q-th largest of P match indexes is a sort + static gather; XLA lowers
the tiny fixed-width sort over the peers axis to a comparator network, which
fuses cleanly into the surrounding step.  See `ops.pallas_quorum` for the
hand-written Pallas variant used when P is large.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def quorum_match_index(match: jax.Array, quorum: int) -> jax.Array:
    """[G, P] match matrix -> [G] q-th largest match index per group."""
    P = match.shape[-1]
    sorted_match = jnp.sort(match, axis=-1)          # ascending
    return sorted_match[..., P - quorum]


def quorum_commit_index(match: jax.Array, log_term: jax.Array,
                        log_len: jax.Array, commit: jax.Array,
                        term: jax.Array, is_leader: jax.Array,
                        *, quorum: int, window: int,
                        term_of=None) -> jax.Array:
    """Advance per-group commit indexes for leader rows; monotone for all.

    `term_of(idx)` overrides the term read (the hot step passes the O(K)
    transition-table reader, core/state.py term_at_tbl); the default
    reads the ring for standalone callers and tests.
    """
    # Deferred import: core.step imports this module, so a module-level
    # import of core.state would be circular when ops loads first.
    from raftsql_tpu.core.state import term_at

    cand = quorum_match_index(match, quorum)
    if term_of is None:
        cand_term = term_at(log_term, log_len, cand, window)
    else:
        cand_term = term_of(cand)
    ok = is_leader & (cand_term == term) & (cand > commit)
    return jnp.where(ok, cand, commit)


def vote_count(votes: jax.Array) -> jax.Array:
    """[G, P] bool vote matrix -> [G] granted-vote counts."""
    return votes.sum(axis=-1)
