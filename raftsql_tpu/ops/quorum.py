"""Quorum / commit-index kernels.

This is the math at the heart of the reference's vendored consensus library
(etcd/raft's `maybeCommit`, driven from the reference's event loop at
raft.go:224-235), recast as vectorized reductions over the `[G, P]`
match-index matrix:

  commit'[g] = the largest index replicated on a quorum of peers, provided
               the entry at that index carries the leader's current term
               (raft §5.4.2 — leaders only commit entries of their own term).

The q-th largest of P match indexes is a sort + static gather; XLA lowers
the tiny fixed-width sort over the peers axis to a comparator network, which
fuses cleanly into the surrounding step.  See `ops.pallas_quorum` for the
hand-written Pallas variant used when P is large.

Dynamic membership (raftsql_tpu/membership/) generalizes the static
"q-th largest of P" to MASK-WEIGHTED quorum: each group carries a
[G, P] voter bitmask (plus a second mask while a joint C_old,new config
is in flight), non-voters contribute -inf to the sort, and the quorum
threshold is a per-group popcount majority — so N groups can sit in N
different configurations inside one fused dispatch.  With a full voter
mask the masked kernels reproduce the static ones bit for bit
(property-tested in tests/test_membership.py).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

I32 = jnp.int32
# Non-voter filler for the masked sort: far below any real match index
# (log positions are small non-negative ints) so a non-voter can never
# be selected as a quorum index.
NON_VOTER = -(1 << 30)


def quorum_match_index(match: jax.Array, quorum: int) -> jax.Array:
    """[G, P] match matrix -> [G] q-th largest match index per group."""
    P = match.shape[-1]
    sorted_match = jnp.sort(match, axis=-1)          # ascending
    return sorted_match[..., P - quorum]


def mask_majority(mask: jax.Array) -> jax.Array:
    """[..., P] bool voter mask -> [...] i32 majority threshold.

    floor(popcount/2) + 1.  An EMPTY mask (all-learner group) yields 1,
    which a masked tally of 0 can never reach — such a group never
    elects and never commits, by construction rather than special case.
    """
    return mask.sum(-1).astype(I32) // 2 + 1


def mask_threshold(mask: jax.Array, size=None) -> jax.Array:
    """Per-group quorum threshold for a voter mask: the mask majority,
    or the explicit flexible-quorum `size` where the mask is FULL.

    A reduced mask (mid membership change, or a seeded partial voter
    set) falls back to its own majority: an explicit size was validated
    against N provisioned slots and carries no intersection guarantee
    over an arbitrary subset — membership/manager.py re-validates the
    geometry across joint halves before letting a change fly.  size
    None compiles to exactly `mask_majority` (the digest-pinned path).
    """
    maj = mask_majority(mask)
    if size is None:
        return maj
    P = mask.shape[-1]
    full = mask.sum(-1).astype(I32) == P
    return jnp.where(full, I32(size), maj)


def masked_vote_count(votes: jax.Array, mask: jax.Array) -> jax.Array:
    """[G, P] bool votes -> [G] granted votes FROM VOTERS only."""
    return jnp.sum(votes & mask, axis=-1).astype(I32)


def masked_vote_win(votes: jax.Array, voters: jax.Array,
                    voters_joint: jax.Array, size=None) -> jax.Array:
    """[G] bool: the vote set wins under the active configuration.

    Joint consensus (raft §6 / the thesis' C_old,new): a candidate needs
    a majority of BOTH masks.  In the stable state voters_joint ==
    voters and the double check degenerates to the single majority.
    `size` is the flexible election-quorum threshold applied to full
    masks (mask_threshold); None keeps the majority kernel bit for bit.
    """
    return (masked_vote_count(votes, voters)
            >= mask_threshold(voters, size)) \
        & (masked_vote_count(votes, voters_joint)
           >= mask_threshold(voters_joint, size))


def masked_quorum_match_index(match: jax.Array, voters: jax.Array,
                              size=None) -> jax.Array:
    """[G, P] match + [G, P] bool voter mask -> [G] mask-weighted
    quorum index: the largest index replicated on a majority of the
    group's voters.  Non-voters contribute NON_VOTER to the sort; the
    per-group majority selects a (data-dependent) sorted position via a
    one-hot reduce — no gather.  With a full mask this is exactly
    `quorum_match_index(match, P // 2 + 1)`; `size` substitutes the
    flexible write-quorum threshold on full masks (mask_threshold)."""
    P = match.shape[-1]
    m = jnp.where(voters, match, NON_VOTER)
    s = jnp.sort(m, axis=-1)                         # ascending
    need = mask_threshold(voters, size)              # [G]
    lanes = jnp.arange(P, dtype=I32)
    sel = lanes == (P - need)[..., None]             # [G, P] one-hot
    got = jnp.sum(jnp.where(sel, s, 0), axis=-1)
    # All-learner group: no voter can supply a quorum index at all.
    return jnp.where(voters.any(-1), got, 0)


def masked_quorum_commit_index(match: jax.Array, log_term: jax.Array,
                               log_len: jax.Array, commit: jax.Array,
                               term: jax.Array, is_leader: jax.Array,
                               *, voters: jax.Array,
                               voters_joint: jax.Array, window: int,
                               term_of=None, size=None) -> jax.Array:
    """`quorum_commit_index` under the active per-group configuration:
    the commit candidate must be replicated on a majority of BOTH masks
    (joint consensus), i.e. the min of the two mask-weighted quorum
    indexes.  Stable groups (joint == voters) reduce to the single-mask
    rule, and a full mask reproduces the static kernel bit for bit —
    or, with `size`, applies the flexible write-quorum threshold."""
    from raftsql_tpu.core.state import term_at

    cand = jnp.minimum(
        masked_quorum_match_index(match, voters, size),
        masked_quorum_match_index(match, voters_joint, size))
    if term_of is None:
        cand_term = term_at(log_term, log_len, cand, window)
    else:
        cand_term = term_of(cand)
    ok = is_leader & (cand_term == term) & (cand > commit)
    return jnp.where(ok, cand, commit)


def quorum_commit_index(match: jax.Array, log_term: jax.Array,
                        log_len: jax.Array, commit: jax.Array,
                        term: jax.Array, is_leader: jax.Array,
                        *, quorum: int, window: int,
                        term_of=None) -> jax.Array:
    """Advance per-group commit indexes for leader rows; monotone for all.

    `term_of(idx)` overrides the term read (the hot step passes the O(K)
    transition-table reader, core/state.py term_at_tbl); the default
    reads the ring for standalone callers and tests.
    """
    # Deferred import: core.step imports this module, so a module-level
    # import of core.state would be circular when ops loads first.
    from raftsql_tpu.core.state import term_at

    cand = quorum_match_index(match, quorum)
    if term_of is None:
        cand_term = term_at(log_term, log_len, cand, window)
    else:
        cand_term = term_of(cand)
    ok = is_leader & (cand_term == term) & (cand > commit)
    return jnp.where(ok, cand, commit)


def vote_count(votes: jax.Array) -> jax.Array:
    """[G, P] bool vote matrix -> [G] granted-vote counts."""
    return votes.sum(axis=-1)
