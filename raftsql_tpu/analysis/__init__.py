"""raftlint — the project-invariant static-analysis suite (ISSUE 13).

`scripts/vet.py` started as a 5-rule `go vet` stand-in; this package
grows it into a checker FRAMEWORK whose passes encode the invariants
this repo has learned the hard way:

  * jit-stability   — jit entry points must keep ONE call signature
                      after boot (PR 12: a mid-flight scalar→mask dtype
                      switch recompiled the step under the leader's
                      election timer and deposed it);
  * determinism     — no wall-clock / unseeded randomness in
                      digest-relevant modules (the chaos plane's
                      bit-reproducibility is an asserted property);
  * thread-ownership— cross-thread attribute writes must hold the
                      attribute's declared lock (PR 7's ring cursors,
                      PR 11's transfer latches);
  * fail-closed     — annotated read-serving functions must terminate
                      every path in an explicit return or raise (PR 12:
                      every unprovable shm read takes the ring path);
  * memory-model    — seqlock code must carry its hardware-ordering
                      assumption as a machine-visible annotation
                      (runtime/shm.py's x86-TSO dependence);
  * the five legacy vet rules (unused imports, duplicate defs, mutable
    defaults, tuple asserts, bare excepts), now per-rule suppressible.

Run it:  `make vet`  or  `python -m raftsql_tpu.analysis [paths...]`.
Suppress one finding:  `# raftlint: disable=<rule>` on (or one line
above) the offending line; project-wide intentional exceptions live in
`analysis/config.py` ALLOWLIST with one-line justifications.

Only the stdlib `ast` module is used — no third-party linters exist in
this environment, and none are needed for project-shaped invariants.
"""
from raftsql_tpu.analysis.core import (Finding, SourceUnit, all_checkers,
                                       run_suite)

__all__ = ["Finding", "SourceUnit", "all_checkers", "run_suite"]
