"""raftlint framework: source model, annotations, suppression, registry.

Everything here is checker-agnostic.  A checker is a class with

    name        unique rule id (kebab-case; what suppressions name)
    doc         one-line invariant statement (``--list`` output)
    check(unit, config)         -> [Finding] for one file
    finish(units, config)       -> [Finding] needing the whole tree
                                   (cross-file call-site analysis)

registered via ``@register``.  ``run_suite`` parses every target file
once into a SourceUnit (AST + raftlint annotations + suppression
table), fans the units through every selected checker, then filters
the findings through per-line suppressions and the project ALLOWLIST.

Annotations are structured comments the passes consume:

    # raftlint: disable=<rule>[,<rule>] [-- why]   suppress on this or
                                                   the next line
    # raftlint: skip-file                          whole file opt-out
    # raftlint: fail-closed                        mark a def for the
                                                   fail-closed pass
    # raftlint: seqlock                            mark a def as seqlock
                                                   protocol code
    # raftlint: assumes=<memory-model>             declare the hardware
                                                   ordering assumption
    # raftlint: owner=<thread>                     declare a method's
                                                   owning thread
    # raftlint: guarded-by=<lock>                  declare the lock an
                                                   attribute write needs

Text after ``--`` is a human justification and is ignored by parsing
but required by review convention for every disable/allowlist entry.
"""
from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence

_ANN_RE = re.compile(r"#\s*raftlint:\s*(.+?)\s*$")


@dataclass(frozen=True)
class Finding:
    path: str
    line: int
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclass
class Annotation:
    """Parsed directives of one ``# raftlint:`` comment."""
    line: int
    flags: set = field(default_factory=set)      # bare words
    values: dict = field(default_factory=dict)   # key=value pairs
    disabled: set = field(default_factory=set)   # disable= rule ids


def _parse_annotations(src: str) -> Dict[int, Annotation]:
    out: Dict[int, Annotation] = {}
    for i, text in enumerate(src.splitlines(), start=1):
        m = _ANN_RE.search(text)
        if not m:
            continue
        body = m.group(1).split("--", 1)[0].strip()
        ann = Annotation(line=i)
        for tok in body.replace(",", " , ").split():
            if tok == ",":
                continue
            if "=" in tok:
                k, v = tok.split("=", 1)
                if k == "disable":
                    ann.disabled.update(
                        r for r in v.split(",") if r)
                else:
                    ann.values[k] = v
            else:
                ann.flags.add(tok)
        # disable=a,b with spaces after commas arrives as extra bare
        # tokens following a disable= — treat trailing bare tokens of a
        # disable annotation as rule ids too.
        if ann.disabled and ann.flags:
            ann.disabled.update(ann.flags)
            ann.flags = set()
        out[i] = ann
    return out


class SourceUnit:
    """One parsed target file."""

    def __init__(self, path: str, relpath: str, src: str,
                 tree: ast.AST):
        self.path = path
        self.relpath = relpath
        self.src = src
        self.tree = tree
        self.lines = src.splitlines()
        self.annotations = _parse_annotations(src)

    # -- annotation helpers ---------------------------------------------

    def skip_file(self) -> bool:
        return any("skip-file" in a.flags
                   for a in self.annotations.values())

    def ann_at(self, line: int) -> Optional[Annotation]:
        return self.annotations.get(line)

    def node_annotation_lines(self, node: ast.AST) -> List[int]:
        """Lines where an annotation may attach to `node`: its own
        line, each decorator's line, and the line above the first."""
        lines = [node.lineno]
        first = node.lineno
        for d in getattr(node, "decorator_list", []):
            lines.append(d.lineno)
            first = min(first, d.lineno)
        lines.append(first - 1)
        return lines

    def node_has_flag(self, node: ast.AST, flag: str) -> bool:
        for ln in self.node_annotation_lines(node):
            a = self.annotations.get(ln)
            if a and flag in a.flags:
                return True
        return False

    def node_value(self, node: ast.AST, key: str) -> Optional[str]:
        for ln in self.node_annotation_lines(node):
            a = self.annotations.get(ln)
            if a and key in a.values:
                return a.values[key]
        return None

    def file_value(self, key: str) -> Optional[str]:
        for a in self.annotations.values():
            if key in a.values:
                return a.values[key]
        return None

    def suppressed(self, f: Finding) -> bool:
        for ln in (f.line, f.line - 1):
            a = self.annotations.get(ln)
            if a and (f.rule in a.disabled or "all" in a.disabled):
                return True
        return False


# -- checker registry ----------------------------------------------------

_CHECKERS: List[type] = []


def register(cls):
    _CHECKERS.append(cls)
    return cls


def all_checkers() -> List[type]:
    # Import for side effect: each module registers its classes.
    from raftsql_tpu.analysis.checkers import (determinism,  # noqa: F401
                                               failclosed,
                                               jit_stability,
                                               ownership, vetrules)
    return list(_CHECKERS)


class Checker:
    """Base class; subclasses override check and/or finish."""

    name = "checker"
    doc = ""
    motivation = ""

    def check(self, unit: SourceUnit, config) -> List[Finding]:
        return []

    def finish(self, units: Sequence[SourceUnit],
               config) -> List[Finding]:
        return []


# -- suite driver --------------------------------------------------------

def iter_py(paths: Iterable[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            yield p
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs if d != "__pycache__"]
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)


def _relpath(path: str) -> str:
    rel = os.path.relpath(path)
    if rel.startswith(".."):
        rel = path
    return rel.replace(os.sep, "/")


def load_unit(path: str) -> SourceUnit:
    with open(path, "r", encoding="utf-8") as f:
        src = f.read()
    tree = ast.parse(src, filename=path)
    return SourceUnit(path, _relpath(path), src, tree)


def unit_from_source(src: str, relpath: str = "<fixture>.py"
                     ) -> SourceUnit:
    """Build a unit from an in-memory snippet (checker fixture tests)."""
    return SourceUnit(relpath, relpath, src, ast.parse(src))


def _allowlisted(f: Finding, config) -> Optional[str]:
    for entry in getattr(config, "allowlist", ()):
        if entry.get("rule") not in (None, f.rule):
            continue
        if entry.get("path") and entry["path"] not in f.path:
            continue
        if entry.get("contains") and entry["contains"] not in f.message:
            continue
        return entry.get("why", "allowlisted")
    return None


def run_units(units: Sequence[SourceUnit], config,
              rules: Optional[Sequence[str]] = None) -> List[Finding]:
    """Run the (selected) checkers over pre-built units; returns the
    surviving findings, sorted by location."""
    checkers = [c() for c in all_checkers()
                if rules is None or c.name in rules]
    findings: List[Finding] = []
    by_path: Dict[str, SourceUnit] = {}
    for u in units:
        by_path[u.path] = u
        by_path[u.relpath] = u
    live_units = [u for u in units if not u.skip_file()]
    for chk in checkers:
        for u in live_units:
            findings.extend(chk.check(u, config))
        findings.extend(chk.finish(live_units, config))
    out = []
    for f in findings:
        u = by_path.get(f.path)
        if u is not None and u.suppressed(f):
            continue
        if _allowlisted(f, config) is not None:
            continue
        out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return out


def run_suite(paths: Sequence[str], config=None,
              rules: Optional[Sequence[str]] = None) -> List[Finding]:
    if config is None:
        from raftsql_tpu.analysis import config as config_mod
        config = config_mod
    units = []
    findings: List[Finding] = []
    for p in iter_py(paths):
        try:
            units.append(load_unit(p))
        except SyntaxError as e:
            findings.append(Finding(_relpath(p), e.lineno or 0,
                                    "syntax", f"syntax error: {e.msg}"))
    findings.extend(run_units(units, config, rules=rules))
    return findings
