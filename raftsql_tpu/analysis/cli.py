"""raftlint command line: `python -m raftsql_tpu.analysis [paths...]`.

Exit status is the contract (CI gates on it): 0 clean, 1 findings,
2 usage error.  `--list` prints the registered rules with their
one-line invariants; `--rules a,b` restricts a run to named rules
(fixture tests and focused pre-commit runs).
"""
from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from raftsql_tpu.analysis import config as config_mod
from raftsql_tpu.analysis.core import all_checkers, run_suite


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="raftlint",
        description="project-invariant static analysis for raftsql_tpu")
    ap.add_argument("paths", nargs="*",
                    default=config_mod.DEFAULT_PATHS,
                    help="files/dirs to check (default: project tree)")
    ap.add_argument("--list", action="store_true", dest="list_rules",
                    help="list registered rules and exit")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids to run (default all)")
    args = ap.parse_args(argv)

    if args.list_rules:
        seen = set()
        for cls in all_checkers():
            if cls.name in seen:
                continue
            seen.add(cls.name)
            print(f"{cls.name:18s} {cls.doc}")
        return 0

    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
        known = {c.name for c in all_checkers()}
        bad = [r for r in rules if r not in known]
        if bad:
            print(f"raftlint: unknown rule(s): {', '.join(bad)}",
                  file=sys.stderr)
            return 2

    findings = run_suite(args.paths, rules=rules)
    for f in findings:
        print(f.render())
    if findings:
        print(f"raftlint: {len(findings)} finding(s)")
        return 1
    print("raftlint: ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
