"""Compile-count tripwire: the runtime falsifier for jit-stability.

The static rule (analysis/checkers/jit_stability.py) argues from call
sites; this module measures the ground truth.  Each named jit entry
point exposes its trace-cache entry count (`fn._cache_size()` on a
jitted callable); the tripwire snapshots the counts when armed and
reports the delta when read.  A steady-state run that compiles an
entry point more than once has, by definition, shipped it a second
trace signature — exactly the mid-flight retrace class that deposed a
healthy leader in PR 12, whatever the static pass thought of the call
sites.

Armed by the chaos fast tier (chaos/run.py prints the verdict OUTSIDE
the digested report — compile counts are host-side facts, not
consensus results) and by the tier-1 test
tests/test_raftlint.py::test_tripwire_single_compile_fused.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional


def _entry_table() -> Dict[str, Callable]:
    from raftsql_tpu.core import cluster, step
    return {
        "cluster_step_jit": cluster.cluster_step_jit,
        "cluster_step_host": cluster.cluster_step_host,
        "cluster_multistep_host": cluster.cluster_multistep_host,
        "cluster_run": cluster.cluster_run,
        "peer_step_jit": step.peer_step_jit,
        "peer_step_packed": step.peer_step_packed,
    }


def cache_size(fn) -> Optional[int]:
    """Trace-cache entry count of a jitted callable, or None when the
    jax build doesn't expose it (tripwire then reports unknown rather
    than failing the run on an introspection gap)."""
    try:
        return int(fn._cache_size())
    except Exception:                        # noqa: BLE001
        return None


class JitTripwire:
    """Snapshot-on-arm / delta-on-read compile counter over the
    project's jit entry points."""

    def __init__(self, entries: Optional[Dict[str, Callable]] = None):
        self.entries = dict(entries) if entries is not None \
            else _entry_table()
        self._base: Dict[str, Optional[int]] = {
            name: cache_size(fn) for name, fn in self.entries.items()}

    def baseline(self, name: str) -> Optional[int]:
        """Cache entries the entry point already had when armed (>0
        means an earlier run in this process warmed it)."""
        return self._base.get(name)

    def compiles(self) -> Dict[str, Optional[int]]:
        """Per-entry compilations since arming (None = unmeasurable)."""
        out: Dict[str, Optional[int]] = {}
        for name, fn in self.entries.items():
            now = cache_size(fn)
            base = self._base[name]
            out[name] = None if now is None or base is None \
                else now - base
        return out

    def offenders(self, limit: int = 1) -> Dict[str, int]:
        """Entry points that compiled MORE than `limit` times since
        arming.  Entries that never ran (0) or can't be measured
        (None) are not offenders."""
        return {name: n for name, n in self.compiles().items()
                if n is not None and n > limit}

    def check(self, limit: int = 1) -> None:
        """Raise if any armed entry point recompiled past `limit` —
        one trace signature per entry point is the invariant."""
        bad = self.offenders(limit)
        if bad:
            raise AssertionError(
                f"jit-stability tripwire: recompiles past limit="
                f"{limit}: {bad} — a second trace signature reached "
                f"a steady-state jit entry point")
