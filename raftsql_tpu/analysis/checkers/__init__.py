"""raftlint passes.  Importing a module registers its checkers."""
