"""determinism — no wall clock / unseeded RNG in digest-relevant code.

The chaos plane's contract is that a seed reproduces bit-for-bit
(`make chaos` runs every seed twice and digest-compares), and the
bench harness compares runs across rounds.  Both break silently the
moment a digest-relevant module reads `time.time()`, an argless
`datetime.now()`, or an unseeded RNG — the run still "passes", it just
stops being evidence.  Scope is config.DETERMINISM_PATHS; the
sanctioned clocks (`time.monotonic*`, `time.perf_counter*`) and keyed
`jax.random` are untouched.  Wall-clock planes (placement timestamps,
client jitter) live in config.ALLOWLIST with justifications.

Rules:
  wall-clock       time.time(), datetime.now()/utcnow() with no tz arg
  unseeded-random  random.<fn>() module globals, random.Random() /
                   numpy default_rng()/RandomState()/seed-free legacy
                   globals with no seed argument
"""
from __future__ import annotations

import ast
from typing import List, Optional

from raftsql_tpu.analysis.core import Checker, Finding, SourceUnit, register

# Module-global `random.<fn>` calls that draw from the process RNG.
_RANDOM_GLOBALS = {
    "random", "randint", "randrange", "choice", "choices", "shuffle",
    "sample", "uniform", "gauss", "random_sample", "getrandbits",
    "betavariate", "expovariate", "normalvariate", "triangular",
}
# numpy legacy global-state draws (np.random.<fn>).
_NP_GLOBALS = {
    "rand", "randn", "randint", "random", "random_sample", "choice",
    "shuffle", "permutation", "uniform", "normal", "standard_normal",
}


def _dotted(node: ast.AST) -> Optional[str]:
    """`a.b.c` -> "a.b.c", else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def in_scope(relpath: str, prefixes) -> bool:
    return any(relpath == p or relpath.startswith(p) for p in prefixes)


@register
class DeterminismChecker(Checker):
    name = "wall-clock"
    doc = ("time.time()/argless datetime.now() in digest-relevant "
           "modules (use time.monotonic or a schedule-derived clock)")

    def check(self, unit: SourceUnit, config) -> List[Finding]:
        if not in_scope(unit.relpath,
                        getattr(config, "DETERMINISM_PATHS", [])):
            return []
        out: List[Finding] = []
        for node in ast.walk(unit.tree):
            if not isinstance(node, ast.Call):
                continue
            dn = _dotted(node.func)
            if dn is None:
                continue
            if dn == "time.time":
                out.append(Finding(
                    unit.relpath, node.lineno, self.name,
                    "time.time() in digest-relevant code — use "
                    "time.monotonic() or the schedule clock"))
            elif dn in ("datetime.now", "datetime.datetime.now",
                        "datetime.utcnow", "datetime.datetime.utcnow") \
                    and not node.args and not node.keywords:
                out.append(Finding(
                    unit.relpath, node.lineno, self.name,
                    f"argless {dn}() in digest-relevant code"))
        return out


@register
class UnseededRandomChecker(Checker):
    name = "unseeded-random"
    doc = ("process-global / unseeded RNG in digest-relevant modules "
           "(derive every stream from the schedule seed)")

    def check(self, unit: SourceUnit, config) -> List[Finding]:
        if not in_scope(unit.relpath,
                        getattr(config, "DETERMINISM_PATHS", [])):
            return []
        out: List[Finding] = []
        for node in ast.walk(unit.tree):
            if not isinstance(node, ast.Call):
                continue
            dn = _dotted(node.func)
            if dn is None:
                continue
            seeded = bool(node.args) or bool(node.keywords)
            if dn.startswith("random.") \
                    and dn.split(".", 1)[1] in _RANDOM_GLOBALS:
                out.append(Finding(
                    unit.relpath, node.lineno, self.name,
                    f"{dn}() draws from the process-global RNG"))
            elif dn == "random.Random" and not seeded:
                out.append(Finding(
                    unit.relpath, node.lineno, self.name,
                    "random.Random() without a seed"))
            elif dn in ("np.random.default_rng",
                        "numpy.random.default_rng") and not seeded:
                out.append(Finding(
                    unit.relpath, node.lineno, self.name,
                    f"{dn}() without a seed"))
            elif dn in ("np.random.RandomState",
                        "numpy.random.RandomState") and not seeded:
                out.append(Finding(
                    unit.relpath, node.lineno, self.name,
                    f"{dn}() without a seed"))
            elif (dn.startswith("np.random.")
                  or dn.startswith("numpy.random.")) \
                    and dn.rsplit(".", 1)[1] in _NP_GLOBALS:
                out.append(Finding(
                    unit.relpath, node.lineno, self.name,
                    f"{dn}() draws from numpy's global state"))
        return out
