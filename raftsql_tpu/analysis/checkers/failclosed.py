"""fail-closed — annotated read paths end in an explicit return/raise.

The shm fast path's whole safety argument (PR 12, ISSUE 12) is that a
read the mapping cannot PROVE fresh falls back to the ring: every
branch of the reader ends in `return None` (ring fallback), a real
result, or a raise.  The failure mode this guards is structural decay:
someone adds an `elif` for a new mode and forgets the final fallback,
and the function falls off the end — which in Python is ALSO
`return None`, so the bug is invisible at the call site and shows up
as a silently widened contract.

`# raftlint: fail-closed` on a def makes the pass prove:

  * the body cannot fall off the end — its final statement chain
    terminates in Return/Raise (If needs both arms, Try needs its
    handlers covered or a terminating finally);
  * no bare `return` — the fallback is spelled `return None` so a
    reviewer can see the branch chose to fail closed;
  * every except handler in the function itself returns or raises —
    a swallowed exception inside a fail-closed path is a silent serve.

`# raftlint: seqlock` marks torn-read-retry protocol code; it requires
the FILE to declare its hardware ordering dependence with a
`# raftlint: assumes=<memory-model>` annotation (rule "memory-model")
— runtime/shm.py's x86-TSO store-order reliance, machine-checked
instead of buried in docstring prose.

config.FAILCLOSED_REQUIRED pins both registries: the listed functions
must carry the listed annotations, so deleting one is a finding, not a
silent scope shrink.
"""
from __future__ import annotations

import ast
from typing import List

from raftsql_tpu.analysis.core import Checker, Finding, SourceUnit, register


def _terminates(stmts) -> bool:
    """True when a statement list cannot fall off its end."""
    if not stmts:
        return False
    last = stmts[-1]
    if isinstance(last, (ast.Return, ast.Raise)):
        return True
    if isinstance(last, ast.If):
        return bool(last.orelse) and _terminates(last.body) \
            and _terminates(last.orelse)
    if isinstance(last, (ast.With, ast.AsyncWith)):
        return _terminates(last.body)
    if isinstance(last, ast.Try):
        if _terminates(last.finalbody):
            return True
        tail = last.orelse if last.orelse else last.body
        return _terminates(tail) \
            and all(_terminates(h.body) for h in last.handlers)
    if isinstance(last, ast.Match):
        has_catchall = any(
            isinstance(c.pattern, ast.MatchAs) and c.pattern.pattern
            is None and c.guard is None for c in last.cases)
        return has_catchall and all(_terminates(c.body)
                                    for c in last.cases)
    # Loops may execute zero times; conservatively non-terminating.
    return False


class _BodyScan(ast.NodeVisitor):
    """Bare returns + swallowing handlers inside ONE function (nested
    defs excluded — they have their own annotation scope)."""

    def __init__(self, unit: SourceUnit, fname: str):
        self.unit = unit
        self.fname = fname
        self.findings: List[Finding] = []

    def visit_FunctionDef(self, node):    # do not descend
        pass

    visit_AsyncFunctionDef = visit_Lambda = visit_FunctionDef

    def visit_Return(self, node):
        if node.value is None:
            self.findings.append(Finding(
                self.unit.relpath, node.lineno, "fail-closed",
                f"{self.fname}: bare `return` — spell the fallback "
                f"(`return None`) so the branch visibly fails closed"))

    def visit_ExceptHandler(self, node):
        if not _terminates(node.body):
            self.findings.append(Finding(
                self.unit.relpath, node.lineno, "fail-closed",
                f"{self.fname}: except handler neither returns nor "
                f"raises — a swallowed exception here is a silent "
                f"serve"))
        self.generic_visit(node)


@register
class FailClosedChecker(Checker):
    name = "fail-closed"
    doc = ("annotated read-serving functions must terminate every "
           "path in an explicit return or raise (ring fallback)")

    def check(self, unit: SourceUnit, config) -> List[Finding]:
        out: List[Finding] = []
        funcs = {}
        for node in ast.walk(unit.tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                funcs.setdefault(node.name, node)
                if not unit.node_has_flag(node, "fail-closed"):
                    continue
                if not _terminates(node.body):
                    out.append(Finding(
                        unit.relpath, node.lineno, self.name,
                        f"{node.name}: body can fall off the end — "
                        f"an implicit `return None` that no reviewer "
                        f"chose; end in explicit return/raise"))
                scan = _BodyScan(unit, node.name)
                for st in node.body:
                    scan.visit(st)
                out.extend(scan.findings)
        # Registry pin: erasing an annotation is a finding.
        for suffix, req in getattr(config, "FAILCLOSED_REQUIRED",
                                   {}).items():
            if not unit.relpath.endswith(suffix):
                continue
            for flag in ("fail-closed", "seqlock"):
                for fname in req.get(flag, ()):
                    node = funcs.get(fname)
                    if node is None:
                        out.append(Finding(
                            unit.relpath, 1, self.name,
                            f"registry names {fname} but no such def "
                            f"exists — update FAILCLOSED_REQUIRED"))
                    elif not unit.node_has_flag(node, flag):
                        out.append(Finding(
                            unit.relpath, node.lineno, self.name,
                            f"{fname} must carry `# raftlint: {flag}` "
                            f"(pinned by FAILCLOSED_REQUIRED)"))
        return out


@register
class MemoryModelChecker(Checker):
    name = "memory-model"
    doc = ("seqlock-annotated protocol code requires a file-level "
           "`assumes=<memory-model>` hardware-ordering declaration")

    def check(self, unit: SourceUnit, config) -> List[Finding]:
        out: List[Finding] = []
        assumed = unit.file_value("assumes")
        for node in ast.walk(unit.tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)) \
                    and unit.node_has_flag(node, "seqlock") \
                    and assumed is None:
                out.append(Finding(
                    unit.relpath, node.lineno, self.name,
                    f"{node.name} is seqlock protocol code but the "
                    f"file declares no `# raftlint: "
                    f"assumes=<memory-model>` — barrier-free seqlocks "
                    f"are only sound under a declared store order "
                    f"(x86-tso here)"))
        return out
