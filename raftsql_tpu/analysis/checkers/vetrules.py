"""The five seed rules of scripts/vet.py, ported into the framework.

Same defect classes the original `go vet` stand-in caught — unused
imports (symbol drift after refactors), duplicate defs in one scope
(silent shadowing), mutable default arguments, `assert (cond, msg)`
tuples (always true), bare `except:` — now individually suppressible
with `# raftlint: disable=<rule>`.
"""
from __future__ import annotations

import ast
from typing import List

from raftsql_tpu.analysis.core import Checker, Finding, SourceUnit, register


@register
class UnusedImportChecker(Checker):
    name = "unused-import"
    doc = "imported name never referenced (symbol drift after refactors)"

    def check(self, unit: SourceUnit, config) -> List[Finding]:
        if unit.relpath.endswith("__init__.py"):
            return []                    # __init__ imports re-export
        imported = {}                    # name -> (lineno, qualified)
        for node in ast.walk(unit.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    name = a.asname or a.name.split(".")[0]
                    imported[name] = (node.lineno, a.name)
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue
                for a in node.names:
                    if a.name == "*":
                        continue
                    name = a.asname or a.name
                    imported[name] = (node.lineno, a.name)
        used = set()
        for node in ast.walk(unit.tree):
            if isinstance(node, ast.Name):
                used.add(node.id)
            elif isinstance(node, ast.Constant) \
                    and isinstance(node.value, str) \
                    and node.value in imported:
                used.add(node.value)     # __all__ / re-export strings
        return [Finding(unit.relpath, lineno, self.name,
                        f"unused import: {qual}")
                for name, (lineno, qual) in sorted(imported.items())
                if name not in used]


@register
class DuplicateDefChecker(Checker):
    name = "duplicate-def"
    doc = "duplicate def in one scope silently shadows the first"

    def check(self, unit: SourceUnit, config) -> List[Finding]:
        out: List[Finding] = []

        def scan(body):
            seen = {}
            for st in body:
                if isinstance(st, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                    decorated = any(
                        (isinstance(d, ast.Name)
                         and d.id in ("property", "overload", "setter"))
                        or isinstance(d, ast.Attribute)
                        for d in st.decorator_list)
                    if st.name in seen and not decorated:
                        out.append(Finding(
                            unit.relpath, st.lineno, self.name,
                            f"duplicate def {st.name} (first at line "
                            f"{seen[st.name]})"))
                    seen.setdefault(st.name, st.lineno)

        for node in ast.walk(unit.tree):
            if isinstance(node, (ast.Module, ast.ClassDef)):
                scan(node.body)
        return out


@register
class MutableDefaultChecker(Checker):
    name = "mutable-default"
    doc = "mutable default argument shared across calls"

    def check(self, unit: SourceUnit, config) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(unit.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for d in node.args.defaults + node.args.kw_defaults:
                    if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                        out.append(Finding(
                            unit.relpath, node.lineno, self.name,
                            f"mutable default arg in {node.name}"))
        return out


@register
class AssertTupleChecker(Checker):
    name = "assert-tuple"
    doc = "assert on a non-empty tuple is always true"

    def check(self, unit: SourceUnit, config) -> List[Finding]:
        return [Finding(unit.relpath, node.lineno, self.name,
                        "assert on a tuple is always true")
                for node in ast.walk(unit.tree)
                if isinstance(node, ast.Assert)
                and isinstance(node.test, ast.Tuple) and node.test.elts]


@register
class BareExceptChecker(Checker):
    name = "bare-except"
    doc = "bare except: catches SystemExit/KeyboardInterrupt too"

    def check(self, unit: SourceUnit, config) -> List[Finding]:
        return [Finding(unit.relpath, node.lineno, self.name,
                        "bare except:")
                for node in ast.walk(unit.tree)
                if isinstance(node, ast.ExceptHandler)
                and node.type is None]
