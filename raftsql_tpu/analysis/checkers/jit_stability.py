"""jit-stability — jit entry points must keep ONE signature after boot.

The defect class (PR 12, batched ReadIndex): a jitted step that is fed
a Python scalar on one call and an array on the next RETRACES — and
the recompile pause lands under the leader's election timer, deposing
a healthy leader.  The cure is structural: decide the argument's
dtype/shape at boot and ship the same form every call (the `[G]`
force-broadcast mask, runtime/node.py's `_ti_arr` constants).

Static heuristics over config.JIT_ENTRY_POINTS call sites:

  (a) cross-site mixing — one call site passes a Python numeric/bool
      literal where another passes a non-literal for the same
      parameter position: two trace signatures by construction;
  (b) conditional literals — an argument (or a local assigned just
      above) of the form `<literal> if c else <expr>`: the scalar/
      array switch inlined;
  (c) `jax.jit(...)` / `functools.partial(jax.jit, ...)` invoked
      inside a loop body: a fresh cache (and a fresh compile) per
      iteration.

A flagged site that is a deliberate boot-time choice gets a
`# raftlint: disable=jit-stability -- why` with its justification.
The static rule is falsifiable at runtime by the compile-count
tripwire (raftsql_tpu/analysis/tripwire.py): one compilation per
entry point across a chaos fast-tier run, asserted in `make chaos`
and tier-1.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Tuple

from raftsql_tpu.analysis.core import Checker, Finding, SourceUnit, register


def _is_literal(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant):
        return isinstance(node.value, (int, float, bool)) \
            and node.value is not None
    if isinstance(node, ast.UnaryOp) \
            and isinstance(node.op, (ast.USub, ast.UAdd)):
        return _is_literal(node.operand)
    return False


def _entry_name(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _mixed_ifexp(node: ast.AST) -> bool:
    """`1 if c else arr` / `arr if c else 1` — a literal on exactly one
    branch is the scalar/array dtype switch inlined."""
    return (isinstance(node, ast.IfExp)
            and _is_literal(node.body) != _is_literal(node.orelse))


def _is_jax_jit(node: ast.AST) -> bool:
    """`jax.jit(...)` or `functools.partial(jax.jit, ...)`."""
    if not isinstance(node, ast.Call):
        return False
    dn = _entry_name(node.func)
    if dn == "jit":
        return True
    if dn == "partial":
        return any(_entry_name(a) == "jit" for a in node.args
                   if isinstance(a, (ast.Name, ast.Attribute)))
    return False


class _SiteVisitor(ast.NodeVisitor):
    """Collects entry-point call sites + per-function IfExp-literal
    locals + jax.jit-in-loop occurrences for one file."""

    def __init__(self, unit: SourceUnit, entries, static_args,
                 collect_sites: bool):
        self.unit = unit
        self.entries = entries
        self.static_args = static_args
        self.collect_sites = collect_sites
        # (entry, argpos|kwname) -> [(relpath, line, is_literal, repr)]
        self.sites: Dict[Tuple[str, object], list] = {}
        self.findings: List[Finding] = []
        self._loop_depth = 0
        # name -> line of `name = <lit> if c else <expr>` in the
        # innermost enclosing function
        self._condlit_stack: List[Dict[str, int]] = [{}]

    # -- loops: jax.jit inside is a fresh compile per iteration --------

    def _visit_loop(self, node):
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    visit_For = visit_While = visit_AsyncFor = _visit_loop

    def _visit_func(self, node):
        self._condlit_stack.append({})
        self.generic_visit(node)
        self._condlit_stack.pop()

    visit_FunctionDef = visit_AsyncFunctionDef = _visit_func

    def visit_Assign(self, node):
        if _mixed_ifexp(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self._condlit_stack[-1][t.id] = node.lineno
        self.generic_visit(node)

    def visit_Call(self, node):
        if self._loop_depth and _is_jax_jit(node):
            self.findings.append(Finding(
                self.unit.relpath, node.lineno, "jit-stability",
                "jax.jit invoked inside a loop — a fresh trace cache "
                "(and compile) per iteration; jit once at boot"))
        name = _entry_name(node.func)
        if name in self.entries:
            self._record_site(name, node)
        self.generic_visit(node)

    def _record_site(self, name: str, node: ast.Call) -> None:
        condlits = self._condlit_stack[-1]
        static = self.static_args.get(name, set())

        def classify(key, arg):
            if key in static:
                return                   # deliberate-recompile params
            if _mixed_ifexp(arg):
                self.findings.append(Finding(
                    self.unit.relpath, arg.lineno, "jit-stability",
                    f"{name}() arg {key}: conditional mixes a Python "
                    f"literal with a non-literal — two trace "
                    f"signatures; ship one dtype/shape from boot"))
                return
            if isinstance(arg, ast.Name) and arg.id in condlits:
                self.findings.append(Finding(
                    self.unit.relpath, node.lineno, "jit-stability",
                    f"{name}() arg {key}: `{arg.id}` (line "
                    f"{condlits[arg.id]}) mixes a Python literal with "
                    f"a non-literal — two trace signatures; ship one "
                    f"dtype/shape from boot"))
                return
            if self.collect_sites:
                self.sites.setdefault((name, key), []).append(
                    (self.unit.relpath, node.lineno, _is_literal(arg),
                     ast.unparse(arg) if hasattr(ast, "unparse")
                     else "<arg>"))

        for i, arg in enumerate(node.args):
            classify(i, arg)
        for kw in node.keywords:
            if kw.arg is not None:
                classify(kw.arg, kw.value)


@register
class JitStabilityChecker(Checker):
    name = "jit-stability"
    doc = ("jit entry points fed varying Python-literal/array forms "
           "after boot retrace mid-flight (recompile deposes leaders)")

    def finish(self, units: Sequence[SourceUnit],
               config) -> List[Finding]:
        entries = getattr(config, "JIT_ENTRY_POINTS", set())
        if not entries:
            return []
        static_args = getattr(config, "JIT_STATIC_ARGS", {})
        skip_mix = tuple(getattr(config, "JIT_SKIP_MIXING_PREFIXES",
                                 ()))
        findings: List[Finding] = []
        sites: Dict[Tuple[str, object], list] = {}
        for unit in units:
            v = _SiteVisitor(unit, entries, static_args,
                             collect_sites=not
                             unit.relpath.startswith(skip_mix))
            v.visit(unit.tree)
            findings.extend(v.findings)
            for k, lst in v.sites.items():
                sites.setdefault(k, []).extend(lst)
        for (entry, key), lst in sorted(sites.items(),
                                        key=lambda kv: str(kv[0])):
            lits = [s for s in lst if s[2]]
            dyns = [s for s in lst if not s[2]]
            if lits and dyns:
                other = dyns[0]
                for (relpath, line, _lit, rep) in lits:
                    findings.append(Finding(
                        relpath, line, self.name,
                        f"{entry}() arg {key}: literal `{rep}` here "
                        f"but non-literal `{other[3]}` at "
                        f"{other[0]}:{other[1]} — two trace "
                        f"signatures for one jit entry point"))
        return findings
