"""thread-ownership — shared attributes are written under their lock.

The serving stack runs three thread populations through one object
graph: the tick thread (ClusterHostPlane.tick), the ring drain / HTTP
worker threads, and the RaftDB apply thread.  Attributes they share
(`_props`/`_queued` proposal queues, `_xfers` transfer latches,
`_q2cb` ack routing, `_tokens` retry LRU) are guarded by a specific
lock; an unguarded write compiles fine and corrupts state only under
load.

The registry is IN the source: an attribute's `__init__` assignment
carries `# raftlint: guarded-by=<lock>`, and every later write to
`self.<attr>` anywhere in the class must be lexically inside
`with self.<lock>:`.  Methods that run strictly on one thread before
or after concurrency exists (boot, close) opt out with
`# raftlint: owner=<thread> -- why`.  config.OWNERSHIP_REQUIRED pins
the registry for the three serving-plane classes so deleting an
annotation is itself a finding.

Writes counted: `self.a = ...`, `self.a[k] = ...`, `self.a += ...`,
`del self.a[k]`, and mutator calls (`self.a.append/extend/add/pop/
update/...`).  Reads are not flagged (racy reads are the lock-free
fast-path idiom this codebase uses deliberately — e.g. `if
self._xfer_req:` before taking the lock).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional

from raftsql_tpu.analysis.core import Checker, Finding, SourceUnit, register

_MUTATORS = {
    "append", "extend", "add", "insert", "remove", "discard", "pop",
    "popleft", "popitem", "appendleft", "clear", "update",
    "setdefault", "move_to_end", "sort", "reverse",
}


def _self_attr_base(node: ast.AST) -> Optional[str]:
    """Peel Subscript/Attribute chains down to `self.<attr>`; returns
    attr or None.  `self.a[k]` -> a; `self.a.b` -> a (writing through
    a sub-object of a guarded attr still mutates shared state)."""
    seen_deeper = False
    while True:
        if isinstance(node, ast.Subscript):
            node = node.value
            seen_deeper = True
        elif isinstance(node, ast.Attribute):
            if isinstance(node.value, ast.Name) \
                    and node.value.id == "self":
                return node.attr
            node = node.value
            seen_deeper = True
        else:
            return None


def _guarded_map(unit: SourceUnit, cls: ast.ClassDef) -> Dict[str, str]:
    """attr -> lock, from guarded-by annotations on __init__ (or any
    method's) `self.<attr> = ...` assignment lines."""
    out: Dict[str, str] = {}
    for node in ast.walk(cls):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        lock = None
        for ln in (node.lineno, node.lineno - 1):
            a = unit.ann_at(ln)
            if a and "guarded-by" in a.values:
                lock = a.values["guarded-by"]
        if lock is None:
            continue
        for t in targets:
            if isinstance(t, ast.Attribute) \
                    and isinstance(t.value, ast.Name) \
                    and t.value.id == "self":
                out[t.attr] = lock
    return out


class _MethodScan(ast.NodeVisitor):
    def __init__(self, unit: SourceUnit, cls_name: str, method: str,
                 guarded: Dict[str, str]):
        self.unit = unit
        self.cls_name = cls_name
        self.method = method
        self.guarded = guarded
        self.held: List[str] = []
        self.findings: List[Finding] = []

    def visit_With(self, node):
        locks = []
        for item in node.items:
            e = item.context_expr
            if isinstance(e, ast.Attribute) \
                    and isinstance(e.value, ast.Name) \
                    and e.value.id == "self":
                locks.append(e.attr)
        self.held.extend(locks)
        for st in node.body:
            self.visit(st)
        for _ in locks:
            self.held.pop()
        # items' context expressions need no scan (no writes there)

    def _flag(self, attr: str, line: int) -> None:
        lock = self.guarded[attr]
        self.findings.append(Finding(
            self.unit.relpath, line, "thread-ownership",
            f"{self.cls_name}.{self.method} writes shared attribute "
            f"`{attr}` outside `with self.{lock}` (declared "
            f"guarded-by={lock})"))

    def _check_write(self, target: ast.AST, line: int) -> None:
        attr = _self_attr_base(target)
        if attr in self.guarded \
                and self.guarded[attr] not in self.held:
            self._flag(attr, line)

    def visit_Assign(self, node):
        for t in node.targets:
            self._check_write(t, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._check_write(node.target, node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self._check_write(node.target, node.lineno)
        self.generic_visit(node)

    def visit_Delete(self, node):
        for t in node.targets:
            self._check_write(t, node.lineno)
        self.generic_visit(node)

    def visit_Call(self, node):
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
            attr = _self_attr_base(f.value)
            if attr in self.guarded \
                    and self.guarded[attr] not in self.held:
                self._flag(attr, node.lineno)
        self.generic_visit(node)


@register
class OwnershipChecker(Checker):
    name = "thread-ownership"
    doc = ("writes to guarded-by annotated attributes must hold the "
           "declared lock (cross-thread write corruption)")

    def check(self, unit: SourceUnit, config) -> List[Finding]:
        out: List[Finding] = []
        required = getattr(config, "OWNERSHIP_REQUIRED", {})
        for node in ast.walk(unit.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            guarded = _guarded_map(unit, node)
            # Registry pin: the named classes must declare (at least)
            # the attrs the config lists — erasing the source
            # annotation is a finding, not a silent scope shrink.
            for (suffix, cls), attrs in required.items():
                if node.name != cls \
                        or not unit.relpath.endswith(suffix):
                    continue
                for attr, lock in attrs.items():
                    if guarded.get(attr) != lock:
                        out.append(Finding(
                            unit.relpath, node.lineno, self.name,
                            f"{cls}.{attr} must carry `# raftlint: "
                            f"guarded-by={lock}` on its __init__ "
                            f"assignment (ownership registry)"))
            if not guarded:
                continue
            for st in node.body:
                if not isinstance(st, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    continue
                if st.name == "__init__":
                    continue             # boot: threads don't exist yet
                if unit.node_value(st, "owner") is not None:
                    continue             # declared single-thread method
                scan = _MethodScan(unit, node.name, st.name, guarded)
                for inner in st.body:
                    scan.visit(inner)
                out.extend(scan.findings)
        return out
