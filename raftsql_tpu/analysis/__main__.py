import sys

from raftsql_tpu.analysis.cli import main

sys.exit(main())
