"""raftlint project configuration: what the passes enforce WHERE.

This file is the project's invariant registry.  Checkers read it via
the `config` argument (tests substitute a stub), so every path scope,
required annotation, and intentional exception is reviewable in one
place — "invariants enforced by tooling, not memory" (ISSUE 13).

Every ALLOWLIST entry carries a one-line justification; an entry
without a living call site is dead weight — delete it when the code it
covers goes.
"""
from __future__ import annotations

# ---------------------------------------------------------------------
# Default target set for `make vet` / `python -m raftsql_tpu.analysis`.
# ---------------------------------------------------------------------
DEFAULT_PATHS = ["raftsql_tpu", "scripts", "tests", "bench.py",
                 "__graft_entry__.py"]

# ---------------------------------------------------------------------
# determinism: modules whose behavior feeds chaos/bench digests must
# not read the wall clock or unseeded RNGs.  Path prefixes (posix).
# `bench.py` + `scripts/` ride along (the bench-guard satellite):
# measurement code must draw load shapes from seeds and intervals from
# monotonic clocks, or run-to-run comparisons are noise.
# ---------------------------------------------------------------------
DETERMINISM_PATHS = [
    "raftsql_tpu/",          # whole runtime tree (api/ exceptions below)
    "bench.py",
    "scripts/",
]

# ---------------------------------------------------------------------
# jit-stability: named jit entry points whose call signature must be
# FIXED after boot.  A call site that can feed a Python scalar on one
# call and an array on another retraces/recompiles mid-flight — under
# the leader's election timer, a recompile pause deposes it (PR 12).
# The checker flags (a) literal/non-literal mixes across call sites of
# one entry point, (b) `x if c else <literal>` feeding an argument,
# and (c) jax.jit invoked inside a loop body.
# ---------------------------------------------------------------------
JIT_ENTRY_POINTS = {
    "cluster_step_jit",
    "cluster_step_host",
    "cluster_multistep_host",
    "cluster_run",
    "peer_step_jit",
    "peer_step_packed",
}

# static_argnums positions (and their keyword spellings): these are
# MEANT to vary as Python values — varying them is a deliberate
# recompile (new cfg, new step count), not the mid-flight class.
JIT_STATIC_ARGS = {
    "cluster_step_jit": {0, "cfg"},
    "cluster_step_host": {0, "cfg"},
    "cluster_multistep_host": {0, 3, "cfg", "steps"},
    "cluster_run": {0, 3, "cfg", "num_ticks"},
    "peer_step_jit": {0, "cfg"},
    "peer_step_packed": {0, "cfg"},
}

# Call sites under these prefixes are excluded from the CROSS-SITE
# mixing rule only: a test deliberately probing both the scalar and
# the vector form is coverage, not a production signature switch.
# (The conditional-literal and jit-in-loop rules still apply there.)
JIT_SKIP_MIXING_PREFIXES = ("tests/",)

# ---------------------------------------------------------------------
# thread-ownership: shared attributes are declared AT the attribute
# (`# raftlint: guarded-by=<lock>` on the __init__ assignment); writes
# anywhere else in the class must hold `with self.<lock>`.  Methods
# that run strictly on the attribute's owning thread opt out with
# `# raftlint: owner=<thread> -- why`.  The table below pins the
# registry: these classes MUST declare at least these guarded
# attributes — deleting the source annotation is itself a finding.
#   (relpath suffix, class name) -> {attr: lock}
# ---------------------------------------------------------------------
OWNERSHIP_REQUIRED = {
    ("runtime/hostplane.py", "ClusterHostPlane"): {
        "_props": "_prop_lock",      # HTTP/client threads extend,
        "_queued": "_prop_lock",     # tick thread pops/re-routes
        "_xfer_req": "_xfer_lock",   # client validate/enqueue vs tick
        "_xfers": "_xfer_lock",      # thread arming the device latch
    },
    ("runtime/db.py", "RaftDB"): {
        "_q2cb": "_mu",              # proposer threads vs apply thread
    },
    ("runtime/ring.py", "RingServer"): {
        "_tokens": "_tok_mu",        # retry-token LRU: drain threads
    },
    ("reshard/coordinator.py", "ReshardCoordinator"): {
        "_cur": "_mu",               # enqueue/doc threads vs the
        "_steps": "_mu",             # step() driver thread
        "_next_id": "_mu",
        "events": "_mu",
        "counters": "_mu",
    },
}

# ---------------------------------------------------------------------
# fail-closed: read-serving functions that must terminate EVERY path
# in an explicit return or raise (the ring fallback is `return None`;
# an implicit fall-through or a swallowed exception is a silent serve).
# Annotated in source with `# raftlint: fail-closed`; the table pins
# the registry so erasing an annotation is a finding.
# `# raftlint: seqlock` marks torn-read-retry protocol code, which
# additionally requires a file-level `assumes=<memory-model>`
# annotation (runtime/shm.py's x86-TSO store-ordering dependence,
# machine-visible instead of docstring prose).
#   relpath suffix -> {"fail-closed": [names], "seqlock": [names]}
# ---------------------------------------------------------------------
FAILCLOSED_REQUIRED = {
    "runtime/shm.py": {
        "fail-closed": ["_snapshot_table", "_catch_up", "try_read",
                        "leader_of"],
        "seqlock": ["_snapshot_table", "_publish_locked"],
    },
    # The router flip is the one place a reshard can lose acked writes
    # (flip before the copy fence) or serve a moved key from the wrong
    # group: every path must end in an explicit publish/return.
    "reshard/coordinator.py": {
        "fail-closed": ["_flip_router"],
    },
    # Overload decisions: a fall-through in admit/check_deadline is a
    # silently unbounded queue; one in brownout_read_path is a silent
    # stale-mode serve.  Every path must end in an explicit
    # return/raise.
    "overload/admission.py": {
        "fail-closed": ["admit", "check_deadline",
                        "brownout_read_path"],
    },
    # The replica's write-fallback budget: a fall-through here admits
    # a redirect lookup past the cap (the stampede the budget exists
    # to shed).
    "replica/node.py": {
        "fail-closed": ["_admit_write"],
    },
}

# ---------------------------------------------------------------------
# Intentional exceptions, each with a one-line justification.  Keys:
#   rule      rule id the exception applies to
#   path      substring of the file's relpath
#   contains  optional substring of the finding message
#   why       REQUIRED human justification
# ---------------------------------------------------------------------
ALLOWLIST = [
    {
        "rule": "wall-clock",
        "path": "raftsql_tpu/placement/controller.py",
        "contains": "time.time()",
        "why": "placement is a wall-clock plane: decision timestamps "
               "are operator-facing epoch times, never digested",
    },
    {
        "rule": "unseeded-random",
        "path": "raftsql_tpu/api/client.py",
        "contains": "random.Random()",
        "why": "client retry jitter is intentionally per-process "
               "nondeterministic; deterministic harnesses inject a "
               "seeded rng via the constructor",
    },
]

# Back-compat alias consumed by core._allowlisted.
allowlist = ALLOWLIST
