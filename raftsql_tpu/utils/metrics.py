"""Node counters + timing helpers.

The reference instantiates etcd's ServerStats/LeaderStats only to satisfy
the transport (reference raft.go:167-176) and never reads them; SURVEY.md
§5.5 asks for real per-node counters instead, exported via the HTTP API
(`GET /metrics` in api/http.py).
"""
from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np


@dataclass
class NodeMetrics:
    ticks: int = 0
    proposals: int = 0
    commits: int = 0
    msgs_sent: int = 0
    elections_won: int = 0
    catchup_appends: int = 0
    compactions: int = 0
    snapshots_sent: int = 0
    snapshots_installed: int = 0
    # Dynamic membership (raftsql_tpu/membership/): committed
    # conf-change entries APPLIED by this node (device masks patched +
    # WAL baseline written).  The companion gauges members_voters /
    # members_learners are computed live from the manager at export
    # time (runtime/db.py metrics()).
    conf_changes_applied: int = 0
    # Quorum geometry (config.py flexible quorums + witness peers):
    # entries fsynced into witness peers' WALs — durability contributed
    # by voters that own no SQLite shard.  The companion gauges
    # quorum.{write_size,election_size,witnesses} are computed from the
    # config at export time (runtime/db.py metrics()).
    witness_appends: int = 0
    # Serving-plane 10x counters (PR 7): WAL group commits — one
    # write+fsync covering EVERY peer's tick records (storage/wal.py
    # GroupCommitWAL) — and double-buffered dispatch ticks, where the
    # previous tick's durable phase ran inside the next dispatch's
    # device window (runtime/hostplane.py overlap pipeline).
    wal_group_commits: int = 0
    overlap_ticks: int = 0
    # Read-plane counters (the lease/ReadIndex/session read plane,
    # runtime/db.py query modes): how each served read was satisfied,
    # plus the lease lifecycle — grants (a linear read served straight
    # from a live lease), expiries (a leader held the read path but its
    # lease had lapsed), degrades (a linear read fell back from the
    # lease fast path to a full ReadIndex quorum round).
    reads_local: int = 0
    reads_session: int = 0
    reads_follower: int = 0
    reads_lease: int = 0
    reads_read_index: int = 0
    lease_grants: int = 0
    lease_expiries: int = 0
    lease_degrades: int = 0
    # Zero-round-trip read plane (PR 12): shm_hits are GETs a worker
    # served from its mapped snapshot without any ring traffic;
    # shm_fallbacks are GETs that tried the shm plane and had to fall
    # back to the ring round trip (stale epoch, publisher behind the
    # requested watermark, log overflow); read_index_batched counts
    # ReadIndex reads confirmed by a SHARED per-tick quorum round
    # (runtime/node.py read batcher) rather than a round of their own.
    # The batch histogram buckets how many reads each confirming round
    # carried (power-of-2 buckets like transfer_stall_hist).
    reads_shm_hits: int = 0
    reads_shm_fallbacks: int = 0
    reads_read_index_batched: int = 0
    read_batch_hist: Dict[str, int] = field(default_factory=dict)
    # Fault counters (chaos/ harness + storage fsio shim): injected
    # message-plane faults and storage faults survived by this node.
    # Zero outside chaos runs; exported so a chaos'd deployment's
    # /metrics names what it was subjected to.
    faults_dropped_msgs: int = 0
    faults_delayed_msgs: int = 0
    faults_partitions: int = 0
    faults_crashes: int = 0
    faults_fsync: int = 0
    # Extended fault matrix (PR 2): corrupt wire frames dropped by the
    # CRC-framed codec (transport/codec.py + tcp/_recv_loop), ENOSPC
    # write failures surfaced by the WAL (storage/fsio.py), fsync
    # latency stalls survived, and per-peer clock-skew timer deviation
    # applied (runtime/fused.py timer_inc seam).  corrupt_frames is ALSO
    # live in production: any bad frame a TCP peer sends is counted
    # here, not just injected ones.
    faults_corrupt_frames: int = 0
    faults_enospc: int = 0
    faults_fsync_stalls: int = 0
    faults_skew_ticks: int = 0
    # Leadership-transfer plane (PR 11): admin/placement-initiated
    # transfers by outcome — initiated (latch armed), completed (the
    # target took leadership), aborted (deadline passed or leadership
    # settled elsewhere; the group re-opened for proposals either way),
    # refused (validation failed: no leader, in-flight transfer,
    # learner/non-voter target).  The stall histogram buckets each
    # finished transfer's proposal-intake pause in ticks (power-of-2
    # buckets, keys are strings so prom_samples renders
    # transfers_stall_ticks_hist{bucket=...}).
    # Pod plane (raftsql_tpu/pod/): the multi-host runtime's cross-host
    # counters — collectives completed (one per tick once the pod is
    # formed), wall time this host spent WAITING in them (the lockstep
    # cost: slowest-host skew + the wire), proposals that arrived from
    # ANOTHER pod host via the gather, durable-commit acks sent as a
    # group-shard owner / received as an origin, and transport bytes.
    # All zero outside --pod deployments.
    pod_gathers: int = 0
    pod_gather_wait_ms: float = 0.0
    pod_proposals_routed: int = 0
    pod_acks_tx: int = 0
    pod_acks_rx: int = 0
    pod_bytes_tx: int = 0
    pod_bytes_rx: int = 0
    transfers_initiated: int = 0
    transfers_completed: int = 0
    transfers_aborted: int = 0
    transfers_refused: int = 0
    transfer_stall_hist: Dict[str, int] = field(default_factory=dict)
    # Per-phase tick wall time, accumulated by RaftNode.tick (SURVEY.md
    # §5.1 live profiling): staging (installs + inbox build) / device
    # step / WAL fsync / send / publish.
    t_stage_ms: float = 0.0
    t_device_ms: float = 0.0
    t_wal_ms: float = 0.0
    t_send_ms: float = 0.0
    t_publish_ms: float = 0.0
    started_at: float = field(default_factory=time.monotonic)

    def note_transfer_stall(self, ticks: int) -> None:
        """Bucket one finished transfer's intake-stall duration."""
        b = 1
        t = max(int(ticks), 1)
        while b < t:
            b <<= 1
        k = str(b)
        self.transfer_stall_hist[k] = self.transfer_stall_hist.get(k, 0) + 1

    def note_read_batch(self, n: int) -> None:
        """Bucket one confirming round's ReadIndex batch size."""
        b = 1
        t = max(int(n), 1)
        while b < t:
            b <<= 1
        k = str(b)
        self.read_batch_hist[k] = self.read_batch_hist.get(k, 0) + 1

    def snapshot(self) -> dict:
        up = max(time.monotonic() - self.started_at, 1e-9)
        t = max(self.ticks, 1)
        return {
            "ticks": self.ticks,
            "proposals": self.proposals,
            "commits": self.commits,
            "msgs_sent": self.msgs_sent,
            "elections_won": self.elections_won,
            "catchup_appends": self.catchup_appends,
            "compactions": self.compactions,
            "snapshots_sent": self.snapshots_sent,
            "snapshots_installed": self.snapshots_installed,
            "conf_changes_applied": self.conf_changes_applied,
            "witness_appends": self.witness_appends,
            "wal_group_commits": self.wal_group_commits,
            "overlap_ticks": self.overlap_ticks,
            "reads": {
                "local": self.reads_local,
                "session": self.reads_session,
                "follower": self.reads_follower,
                "lease": self.reads_lease,
                "read_index": self.reads_read_index,
                "lease_grants": self.lease_grants,
                "lease_expiries": self.lease_expiries,
                "lease_degrades": self.lease_degrades,
                "shm_hits": self.reads_shm_hits,
                "shm_fallbacks": self.reads_shm_fallbacks,
                "read_index_batched": self.reads_read_index_batched,
                "batch_hist": dict(self.read_batch_hist),
            },
            "faults": {
                "dropped_msgs": self.faults_dropped_msgs,
                "delayed_msgs": self.faults_delayed_msgs,
                "partitions": self.faults_partitions,
                "crashes": self.faults_crashes,
                "fsync": self.faults_fsync,
                "corrupt_frames": self.faults_corrupt_frames,
                "enospc": self.faults_enospc,
                "fsync_stalls": self.faults_fsync_stalls,
                "skew_ticks": self.faults_skew_ticks,
            },
            "pod": {
                "gathers": self.pod_gathers,
                "gather_wait_ms": round(self.pod_gather_wait_ms, 3),
                "proposals_routed": self.pod_proposals_routed,
                "acks_tx": self.pod_acks_tx,
                "acks_rx": self.pod_acks_rx,
                "bytes_tx": self.pod_bytes_tx,
                "bytes_rx": self.pod_bytes_rx,
            },
            "transfers": {
                "initiated": self.transfers_initiated,
                "completed": self.transfers_completed,
                "aborted": self.transfers_aborted,
                "refused": self.transfers_refused,
                "stall_ticks_hist": dict(self.transfer_stall_hist),
            },
            "uptime_s": round(up, 3),
            "commits_per_s": round(self.commits / up, 3),
            "phase_ms_per_tick": {
                "stage": round(self.t_stage_ms / t, 4),
                "device": round(self.t_device_ms / t, 4),
                "wal": round(self.t_wal_ms / t, 4),
                "send": round(self.t_send_ms / t, 4),
                "publish": round(self.t_publish_ms / t, 4),
            },
        }


class GroupTraffic:
    """Host-side `[G]` propose/commit/ack counters + EWMA rates — the
    per-group traffic feed for `GET /metrics` (`group_traffic`) and the
    future placement controller (ROADMAP: traffic-aware leadership
    migration needs per-group propose rates to find hot groups).

    Counters are stamped where the host plane already walks per-group
    structures (runtime/hostplane.py: `_stage_ranges` for proposals,
    `_publish_shard` for commits; runtime/db.py `_ack_one` for acks) —
    one vectorized `np.add.at` per tick, no new device work.  Commit
    updates arrive from per-shard publish workers over DISJOINT group
    blocks, so the unsynchronized adds never race on an element.  Rates
    are EWMA'd lazily at scrape time (nothing on the tick path)."""

    def __init__(self, num_groups: int, alpha: float = 0.3,
                 top_k: int = 10):
        G = num_groups
        self.num_groups = G
        self.proposed = np.zeros(G, np.int64)
        self.committed = np.zeros(G, np.int64)
        self.acked = np.zeros(G, np.int64)
        self.top_k = int(os.environ.get("RAFTSQL_METRICS_TOPK", top_k))
        self._alpha = alpha
        self._rate_p = np.zeros(G)
        self._rate_c = np.zeros(G)
        self._last_p = np.zeros(G, np.int64)
        self._last_c = np.zeros(G, np.int64)
        self._last_t = time.monotonic()
        self._mu = threading.Lock()

    # -- hot path (tick thread / publish workers / commit consumer) ----

    def add_propose(self, groups, counts) -> None:
        np.add.at(self.proposed, groups, counts)

    def add_commit(self, groups, counts) -> None:
        np.add.at(self.committed, groups, counts)

    def add_ack(self, group: int) -> None:
        self.acked[group] += 1

    # -- scrape path ----------------------------------------------------

    def _advance_rates_locked(self) -> None:
        now = time.monotonic()
        dt = now - self._last_t
        if dt < 0.05:       # back-to-back scrapes: keep the last window
            return
        inst_p = (self.proposed - self._last_p) / dt
        inst_c = (self.committed - self._last_c) / dt
        a = self._alpha
        self._rate_p += a * (inst_p - self._rate_p)
        self._rate_c += a * (inst_c - self._rate_c)
        self._last_p = self.proposed.copy()
        self._last_c = self.committed.copy()
        self._last_t = now

    def doc(self, leader_of=None, shard_of=None,
            k: Optional[int] = None, transferring=None) -> dict:
        """Aggregate totals + the top-K hot-groups table
        (group id, 1-based leader, EWMA propose/commit rates, raw
        totals; a `shard` column on sharded runtimes so the placement
        story can move hot groups between shards; a `transferring`
        flag when the runtime supplies the set of groups with a
        leadership transfer in flight)."""
        with self._mu:
            self._advance_rates_locked()
            rp = self._rate_p.copy()
            rc = self._rate_c.copy()
        k = min(k if k is not None else self.top_k, self.num_groups)
        # Rate-first ranking with the all-time totals as tie-breaker
        # (a scrape before any rate window still ranks by volume).
        order = np.lexsort((-self.proposed, -rp))[:k]
        hot: List[dict] = []
        for g in order.tolist():
            if not (self.proposed[g] or self.committed[g]
                    or rp[g] > 0):
                continue
            row = {"group": g,
                   "leader": (int(leader_of(g)) + 1
                              if leader_of is not None else 0),
                   "propose_rate": round(float(rp[g]), 3),
                   "commit_rate": round(float(rc[g]), 3),
                   "proposed": int(self.proposed[g]),
                   "committed": int(self.committed[g]),
                   "acked": int(self.acked[g])}
            if callable(shard_of):
                row["shard"] = int(shard_of(g))
            if transferring is not None:
                row["transferring"] = g in transferring
            hot.append(row)
        return {"proposed": int(self.proposed.sum()),
                "committed": int(self.committed.sum()),
                "acked": int(self.acked.sum()),
                "hot_groups": hot}


# ---------------------------------------------------------------------------
# Prometheus text exposition (GET /metrics?format=prom).


PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def wants_prom(query: str, accept: str) -> bool:
    """Content negotiation for GET /metrics: `?format=prom` wins, else
    an Accept header asking for the Prometheus text exposition
    (`application/openmetrics-text` or `text/plain; version=0.0.4`)."""
    if "format=prom" in (query or ""):
        return True
    a = (accept or "").lower()
    return "openmetrics" in a or "version=0.0.4" in a


def _prom_name(s: str) -> str:
    return "".join(c if c.isalnum() or c == "_" else "_" for c in s)


def _prom_label_value(v) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def prom_samples(doc: dict, prefix: str = "raftsql"
                 ) -> List[Tuple[str, Dict[str, str], float]]:
    """Flatten a metrics() JSON document into Prometheus samples
    [(name, labels, value)].  One mapping owns both the exposition and
    the round-trip check (scripts/check_prom.py): every numeric leaf of
    the JSON becomes exactly one sample.

      * nested dicts join with `_` (faults.crashes ->
        raftsql_faults_crashes);
      * dicts keyed by digit strings become bucket-labeled samples
        (wal_gc_batch_hist -> raftsql_wal_gc_batch_hist{bucket="3"});
      * `phase_profile` becomes the summary raftsql_tick_phase_ms
        {phase=...,quantile=...} + _count/_sum/_max series;
      * `group_traffic.hot_groups` rows become raftsql_group_<field>
        {group=...,leader=...[,shard=...]} gauges;
      * None / NaN / strings are skipped (a scrape must always render).
    """
    out: List[Tuple[str, Dict[str, str], float]] = []

    def num(v):
        if isinstance(v, bool):
            return float(v)
        if isinstance(v, (int, float)) and v == v:
            return float(v)
        return None

    def add(name, labels, v):
        fv = num(v)
        if fv is not None:
            out.append((name, labels, fv))

    def walk(obj, name):
        if isinstance(obj, dict):
            if obj and all(isinstance(k, str) and k.lstrip("-").isdigit()
                           for k in obj) \
                    and all(num(v) is not None for v in obj.values()):
                for k, v in sorted(obj.items(), key=lambda kv: int(kv[0])):
                    add(name, {"bucket": k}, v)
                return
            for k, v in obj.items():
                walk(v, f"{name}_{_prom_name(k)}")
        else:
            add(name, {}, obj)

    for key, val in doc.items():
        if key == "phase_profile" and isinstance(val, dict):
            base = f"{prefix}_tick_phase_ms"
            for phase, st in val.items():
                if not isinstance(st, dict):
                    add(f"{prefix}_phase_profile_{_prom_name(phase)}",
                        {}, st)
                    continue
                lab = {"phase": phase}
                for q, f in (("0.5", "p50_ms"), ("0.95", "p95_ms"),
                             ("0.99", "p99_ms")):
                    if f in st:
                        add(base, {**lab, "quantile": q}, st[f])
                add(f"{base}_count", lab, st.get("n"))
                add(f"{base}_sum", lab, st.get("total_ms"))
                # max is not a summary-family suffix: standalone gauge.
                add(f"{prefix}_tick_phase_max_ms", lab,
                    st.get("max_ms"))
            continue
        if key == "group_traffic" and isinstance(val, dict):
            for k, v in val.items():
                if k != "hot_groups":
                    add(f"{prefix}_group_traffic_{_prom_name(k)}", {}, v)
            for row in val.get("hot_groups", ()):
                lab = {"group": str(row.get("group"))}
                if "leader" in row:
                    lab["leader"] = str(row["leader"])
                if "shard" in row:
                    lab["shard"] = str(row["shard"])
                for f, v in row.items():
                    if f in ("group", "leader", "shard"):
                        continue
                    add(f"{prefix}_group_{_prom_name(f)}", lab, v)
            continue
        walk(val, f"{prefix}_{_prom_name(key)}")
    return out


def prom_render(doc: dict, prefix: str = "raftsql") -> str:
    """The Prometheus text exposition of a metrics() document: samples
    grouped per metric name behind one # HELP/# TYPE pair (the format
    requires a metric's samples contiguous), gauges throughout except
    the tick-phase summary."""
    samples = prom_samples(doc, prefix)
    grouped: "Dict[str, List[Tuple[Dict[str, str], float]]]" = {}
    order: List[str] = []
    for name, labels, value in samples:
        if name not in grouped:
            grouped[name] = []
            order.append(name)
        grouped[name].append((labels, value))
    summary = f"{prefix}_tick_phase_ms"
    lines: List[str] = []
    for name in order:
        if name in (summary + "_count", summary + "_sum"):
            # Part of the summary family declared at `summary` — the
            # exposition format forbids a second TYPE for them.
            pass
        else:
            lines.append(f"# HELP {name} raftsql metric {name}")
            lines.append(f"# TYPE {name} "
                         + ("summary" if name == summary else "gauge"))
        for labels, value in grouped[name]:
            lab = ""
            if labels:
                lab = "{" + ",".join(
                    f'{_prom_name(k)}="{_prom_label_value(v)}"'
                    for k, v in labels.items()) + "}"
            if value == int(value) and abs(value) < 2 ** 53:
                sval = str(int(value))
            else:
                sval = repr(value)
            lines.append(f"{name}{lab} {sval}")
    return "\n".join(lines) + "\n"


class LatencyTimer:
    """Thread-safe propose→commit latency sampler (p50 north-star metric).

    A ring of the most recent `cap` samples, so percentiles track
    steady-state latency instead of freezing on compile-stall-dominated
    startup samples."""

    def __init__(self, cap: int = 4096):
        self._samples: list[float] = []
        self._cap = cap
        self._next = 0
        self._lock = threading.Lock()

    def record(self, seconds: float) -> None:
        with self._lock:
            if len(self._samples) < self._cap:
                self._samples.append(seconds)
            else:
                self._samples[self._next] = seconds
                self._next = (self._next + 1) % self._cap

    def percentile(self, q: float) -> float:
        return self.percentiles((q,))[0]

    def percentiles(self, qs) -> list:
        """Percentile per q in `qs`, from ONE snapshot + sort.

        The copy happens under the lock; the O(n log n) sort does NOT —
        a /metrics scrape sorting 4096 samples inside the lock would
        stall every record() on the tick hot path for the duration.
        NaN when empty."""
        with self._lock:
            s = list(self._samples)
        if not s:
            return [float("nan")] * len(qs)
        s.sort()
        return [s[min(int(q * len(s)), len(s) - 1)] for q in qs]
