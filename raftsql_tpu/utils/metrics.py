"""Node counters + timing helpers.

The reference instantiates etcd's ServerStats/LeaderStats only to satisfy
the transport (reference raft.go:167-176) and never reads them; SURVEY.md
§5.5 asks for real per-node counters instead, exported via the HTTP API
(`GET /metrics` in api/http.py).
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field


@dataclass
class NodeMetrics:
    ticks: int = 0
    proposals: int = 0
    commits: int = 0
    msgs_sent: int = 0
    elections_won: int = 0
    catchup_appends: int = 0
    compactions: int = 0
    snapshots_sent: int = 0
    snapshots_installed: int = 0
    # Dynamic membership (raftsql_tpu/membership/): committed
    # conf-change entries APPLIED by this node (device masks patched +
    # WAL baseline written).  The companion gauges members_voters /
    # members_learners are computed live from the manager at export
    # time (runtime/db.py metrics()).
    conf_changes_applied: int = 0
    # Serving-plane 10x counters (PR 7): WAL group commits — one
    # write+fsync covering EVERY peer's tick records (storage/wal.py
    # GroupCommitWAL) — and double-buffered dispatch ticks, where the
    # previous tick's durable phase ran inside the next dispatch's
    # device window (runtime/hostplane.py overlap pipeline).
    wal_group_commits: int = 0
    overlap_ticks: int = 0
    # Fault counters (chaos/ harness + storage fsio shim): injected
    # message-plane faults and storage faults survived by this node.
    # Zero outside chaos runs; exported so a chaos'd deployment's
    # /metrics names what it was subjected to.
    faults_dropped_msgs: int = 0
    faults_delayed_msgs: int = 0
    faults_partitions: int = 0
    faults_crashes: int = 0
    faults_fsync: int = 0
    # Extended fault matrix (PR 2): corrupt wire frames dropped by the
    # CRC-framed codec (transport/codec.py + tcp/_recv_loop), ENOSPC
    # write failures surfaced by the WAL (storage/fsio.py), fsync
    # latency stalls survived, and per-peer clock-skew timer deviation
    # applied (runtime/fused.py timer_inc seam).  corrupt_frames is ALSO
    # live in production: any bad frame a TCP peer sends is counted
    # here, not just injected ones.
    faults_corrupt_frames: int = 0
    faults_enospc: int = 0
    faults_fsync_stalls: int = 0
    faults_skew_ticks: int = 0
    # Per-phase tick wall time, accumulated by RaftNode.tick (SURVEY.md
    # §5.1 live profiling): staging (installs + inbox build) / device
    # step / WAL fsync / send / publish.
    t_stage_ms: float = 0.0
    t_device_ms: float = 0.0
    t_wal_ms: float = 0.0
    t_send_ms: float = 0.0
    t_publish_ms: float = 0.0
    started_at: float = field(default_factory=time.monotonic)

    def snapshot(self) -> dict:
        up = max(time.monotonic() - self.started_at, 1e-9)
        t = max(self.ticks, 1)
        return {
            "ticks": self.ticks,
            "proposals": self.proposals,
            "commits": self.commits,
            "msgs_sent": self.msgs_sent,
            "elections_won": self.elections_won,
            "catchup_appends": self.catchup_appends,
            "compactions": self.compactions,
            "snapshots_sent": self.snapshots_sent,
            "snapshots_installed": self.snapshots_installed,
            "conf_changes_applied": self.conf_changes_applied,
            "wal_group_commits": self.wal_group_commits,
            "overlap_ticks": self.overlap_ticks,
            "faults": {
                "dropped_msgs": self.faults_dropped_msgs,
                "delayed_msgs": self.faults_delayed_msgs,
                "partitions": self.faults_partitions,
                "crashes": self.faults_crashes,
                "fsync": self.faults_fsync,
                "corrupt_frames": self.faults_corrupt_frames,
                "enospc": self.faults_enospc,
                "fsync_stalls": self.faults_fsync_stalls,
                "skew_ticks": self.faults_skew_ticks,
            },
            "uptime_s": round(up, 3),
            "commits_per_s": round(self.commits / up, 3),
            "phase_ms_per_tick": {
                "stage": round(self.t_stage_ms / t, 4),
                "device": round(self.t_device_ms / t, 4),
                "wal": round(self.t_wal_ms / t, 4),
                "send": round(self.t_send_ms / t, 4),
                "publish": round(self.t_publish_ms / t, 4),
            },
        }


class LatencyTimer:
    """Thread-safe propose→commit latency sampler (p50 north-star metric).

    A ring of the most recent `cap` samples, so percentiles track
    steady-state latency instead of freezing on compile-stall-dominated
    startup samples."""

    def __init__(self, cap: int = 4096):
        self._samples: list[float] = []
        self._cap = cap
        self._next = 0
        self._lock = threading.Lock()

    def record(self, seconds: float) -> None:
        with self._lock:
            if len(self._samples) < self._cap:
                self._samples.append(seconds)
            else:
                self._samples[self._next] = seconds
                self._next = (self._next + 1) % self._cap

    def percentile(self, q: float) -> float:
        return self.percentiles((q,))[0]

    def percentiles(self, qs) -> list:
        """Percentile per q in `qs`, from ONE snapshot + sort.

        The copy happens under the lock; the O(n log n) sort does NOT —
        a /metrics scrape sorting 4096 samples inside the lock would
        stall every record() on the tick hot path for the duration.
        NaN when empty."""
        with self._lock:
            s = list(self._samples)
        if not s:
            return [float("nan")] * len(qs)
        s.sort()
        return [s[min(int(q * len(s)), len(s) - 1)] for q in qs]
