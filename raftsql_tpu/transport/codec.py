"""Binary codec for TickBatch — flat, fixed-width, little-endian.

SURVEY.md §2b V2 calls for a flat array-friendly encoding at the wire
boundary instead of protobuf: every record is a fixed-layout struct with
byte payloads appended, so encode/decode is a linear scan with no schema
machinery.  The same layout is shared with the C++ runtime shim.

Frame := u32 n_votes  | VoteRec*
         u32 n_appends| AppendRec*
         u32 n_props  | ProposalRec*
         u32 n_snaps  | SnapshotRec*
         [ColSection]                      (trailing, optional)
VoteRec     := u32 group | u8 type | q term | q last_idx | q last_term | u8 granted
AppendRec   := u32 group | u8 type | q term | q prev_idx | q prev_term
             | q commit | u8 success | q match | q seq | u16 n
             | q ent_term * n | (u32 len | bytes) * n_payloads(=n for REQ, 0 resp)
ProposalRec := u32 group | u32 len | bytes
SnapshotRec := u32 group | q last_idx | q last_term | q term | u32 len | bytes
ColSection  := u32 nv | (i32[nv] per field: v_group v_type v_term v_last_idx
                         v_last_term v_granted — fields omitted when nv==0)
             | u32 na | (i32[na] per field: a_group a_type a_term a_prev_idx
                         a_prev_term a_commit a_success a_match,
                         then i64[na] a_seq — omitted when na==0)
The ColSection carries the columnar payload-free fast path (base.py
ColRecs): raw little-endian array dumps, decoded with zero per-record
work.  Decoders treat trailing bytes after the snapshot section as a
ColSection; its presence is optional for senders.

CRC framing (the wire transports' form):

    framed := u32 crc32(frame) | frame

`encode_batch_framed`/`decode_batch_framed` wrap the flat encoding in a
whole-frame CRC32, and `decode_batch` itself bounds-validates every
declared count/length against the remaining bytes — so a corrupted,
truncated, or Byzantine frame surfaces as `FrameCorruptError` (or
`struct.error`) at the codec boundary, for the receiver to DROP and
count, never as an out-of-bounds read, a silently-truncated payload, or
a crashed recv thread.  The reference trusts rafthttp framing outright
(reference raft.go:268-270); a multi-host deployment cannot.
"""
from __future__ import annotations

import struct
import zlib
from typing import List, Tuple

import numpy as np

from raftsql_tpu.config import MSG_REQ
from raftsql_tpu.transport.base import (AppendRec, ColRecs, ProposalRec,
                                        SnapshotRec, TickBatch, VoteRec)

_U32 = struct.Struct("<I")
_VOTE = struct.Struct("<IBqqqB")
_APP = struct.Struct("<IBqqqqBqqH")
_PLEN = struct.Struct("<I")
_SNAP = struct.Struct("<Iqqq")


class FrameCorruptError(ValueError):
    """A wire frame failed its CRC or structural validation: drop the
    frame (and count it) — raft re-sends, and a bad peer must not crash
    the receiver."""


# ---------------------------------------------------------------------------
# Conf-change entries (dynamic membership, raftsql_tpu/membership/).
#
# A membership change travels THROUGH the replicated log as a marked
# entry payload — the new record kind of the entry plane.  The first
# byte discriminates against the other payload forms on the wire and in
# the WAL: 0x01 = proposal envelope, 0x02/0x04 = snapshot wrappers
# (runtime/envelope.py), printable bytes = bare SQL.  Conf entries are
# NEVER enveloped (their apply is idempotent by log index, and the
# publish plane must recognize them with one leading-byte test), and
# they are scrubbed from the SQL apply stream at commit — the apply
# plane sees an empty entry where a conf change sat, exactly like the
# reference skipping empty/conf entries (raft.go:84-87).
#
# Every conf entry carries the FULL target configuration (voter mask,
# joint mask, learner mask as u64 slot bitmasks — P <= 64), so applying
# one is an unconditional set: re-delivery, forward-retry, and replay
# are idempotent, and the newest entry alone describes the active
# config.  Two-phase joint style (C_old,new -> C_new, one in flight per
# group, raftsql_tpu/membership/manager.py):
#   ENTER_JOINT: voters = C_new, joint = C_old  (both majorities rule)
#   LEAVE_JOINT: voters = joint = C_new         (stable again)
#   LEARNER:     voter masks unchanged, learner set edited (1-phase —
#                learners are outside every quorum, so no joint needed)

CONF_MAGIC = 0x03
CONF_PREFIX = bytes([CONF_MAGIC])
CONF_KIND_LEARNER = 1
CONF_KIND_ENTER_JOINT = 2
CONF_KIND_LEAVE_JOINT = 3
_CONF = struct.Struct("<BBQQQ")     # magic, kind, voters, joint, learners


def encode_conf_entry(kind: int, voters_mask: int, joint_mask: int,
                      learners_mask: int) -> bytes:
    return _CONF.pack(CONF_MAGIC, kind, voters_mask, joint_mask,
                      learners_mask)


def is_conf_entry(data: bytes) -> bool:
    return len(data) == _CONF.size and data[0] == CONF_MAGIC


def decode_conf_entry(data: bytes):
    """(kind, voters_mask, joint_mask, learners_mask), or None when the
    payload is not a conf entry."""
    if not is_conf_entry(data):
        return None
    _, kind, voters, joint, learners = _CONF.unpack(data)
    return kind, voters, joint, learners


def encode_batch(batch: TickBatch) -> bytes:
    out = [_U32.pack(len(batch.votes))]
    for v in batch.votes:
        out.append(_VOTE.pack(v.group, v.type, v.term, v.last_idx,
                              v.last_term, int(v.granted)))
    out.append(_U32.pack(len(batch.appends)))
    for a in batch.appends:
        out.append(_APP.pack(a.group, a.type, a.term, a.prev_idx,
                             a.prev_term, a.commit, int(a.success), a.match,
                             a.seq, len(a.ent_terms)))
        out.append(struct.pack(f"<{len(a.ent_terms)}q", *a.ent_terms))
        if a.type == MSG_REQ:
            assert len(a.payloads) == len(a.ent_terms), \
                "append REQ must carry one payload per entry"
            for p in a.payloads:
                out.append(_PLEN.pack(len(p)))
                out.append(p)
    out.append(_U32.pack(len(batch.proposals)))
    for pr in batch.proposals:
        out.append(_U32.pack(pr.group))
        out.append(_PLEN.pack(len(pr.payload)))
        out.append(pr.payload)
    out.append(_U32.pack(len(batch.snapshots)))
    for s in batch.snapshots:
        out.append(_SNAP.pack(s.group, s.last_idx, s.last_term, s.term))
        out.append(_PLEN.pack(len(s.blob)))
        out.append(s.blob)
    # Columnar section (trailing, optional): raw little-endian array
    # bytes — no per-record packing at all (base.py ColRecs).
    c = batch.cols
    if c is not None and (c.n_votes() or c.n_appends()):
        out.append(_U32.pack(c.n_votes()))
        if c.n_votes():
            for f in _COL_V:
                out.append(np.ascontiguousarray(
                    getattr(c, f), dtype=np.int32).tobytes())
        out.append(_U32.pack(c.n_appends()))
        if c.n_appends():
            for f in _COL_A:
                out.append(np.ascontiguousarray(
                    getattr(c, f),
                    dtype=np.int64 if f == "a_seq" else np.int32).tobytes())
    return b"".join(out)


_COL_V = ("v_group", "v_type", "v_term", "v_last_idx", "v_last_term",
          "v_granted")
_COL_A = ("a_group", "a_type", "a_term", "a_prev_idx", "a_prev_term",
          "a_commit", "a_success", "a_match", "a_seq")


def decode_batch(blob: bytes) -> TickBatch:
    """Decode one flat frame, bounds-validating EVERY declared count and
    length against the remaining bytes.  A frame that declares more
    records/bytes than it carries (truncation, corruption, or a hostile
    peer) raises struct.error — the original blob slicing silently
    truncated payloads instead, handing short entry bytes to the raft
    log."""
    off = 0
    end = len(blob)

    def take(fmt: struct.Struct) -> Tuple:
        nonlocal off
        vals = fmt.unpack_from(blob, off)
        off += fmt.size
        return vals

    def need(nbytes: int, what: str) -> None:
        if nbytes < 0 or end - off < nbytes:
            raise struct.error(
                f"frame truncated in {what}: {nbytes} bytes declared, "
                f"{end - off} remain")

    batch = TickBatch()
    (nv,) = take(_U32)
    need(nv * _VOTE.size, "vote section")
    for _ in range(nv):
        g, t, term, li, lt, gr = take(_VOTE)
        batch.votes.append(VoteRec(group=g, type=t, term=term, last_idx=li,
                                   last_term=lt, granted=bool(gr)))
    (na,) = take(_U32)
    need(na * _APP.size, "append section")
    for _ in range(na):
        g, t, term, pi, pt, cm, su, ma, seq, n = take(_APP)
        need(8 * n, "append entry terms")
        terms = list(struct.unpack_from(f"<{n}q", blob, off))
        off += 8 * n
        payloads: List[bytes] = []
        if t == MSG_REQ:
            for _ in range(n):
                (plen,) = take(_PLEN)
                need(plen, "append payload")
                payloads.append(blob[off:off + plen])
                off += plen
        batch.appends.append(AppendRec(
            group=g, type=t, term=term, prev_idx=pi, prev_term=pt,
            ent_terms=terms, payloads=payloads, commit=cm,
            success=bool(su), match=ma, seq=seq))
    (np_,) = take(_U32)
    need(np_ * (_U32.size + _PLEN.size), "proposal section")
    for _ in range(np_):
        (g,) = take(_U32)
        (plen,) = take(_PLEN)
        need(plen, "proposal payload")
        batch.proposals.append(ProposalRec(group=g,
                                           payload=blob[off:off + plen]))
        off += plen
    if off < len(blob):
        (ns,) = take(_U32)
        need(ns * (_SNAP.size + _PLEN.size), "snapshot section")
        for _ in range(ns):
            g, li, lt, term = take(_SNAP)
            (blen,) = take(_PLEN)
            need(blen, "snapshot blob")
            batch.snapshots.append(SnapshotRec(
                group=g, last_idx=li, last_term=lt, term=term,
                blob=blob[off:off + blen]))
            off += blen
    if off < len(blob):
        cols = ColRecs()
        (nv_,) = take(_U32)
        # Bound-check declared counts against the remaining bytes BEFORE
        # any frombuffer: a truncated or corrupt frame must surface as a
        # codec-level decode error (struct.error, matching the record
        # sections above), not a ValueError deep inside numpy.
        if nv_ * 4 * len(_COL_V) > len(blob) - off:
            raise struct.error(
                f"columnar vote section truncated: {nv_} rows declared, "
                f"{len(blob) - off} bytes remain")
        for f in _COL_V:
            arr = np.frombuffer(blob, np.dtype("<i4"), nv_, off)
            setattr(cols, f, arr)
            off += 4 * nv_
        (na_,) = take(_U32)
        if na_ * (4 * (len(_COL_A) - 1) + 8) > len(blob) - off:
            raise struct.error(
                f"columnar append section truncated: {na_} rows declared, "
                f"{len(blob) - off} bytes remain")
        for f in _COL_A:
            dt = np.dtype("<i8") if f == "a_seq" else np.dtype("<i4")
            arr = np.frombuffer(blob, dt, na_, off)
            setattr(cols, f, arr)
            off += dt.itemsize * na_
        if nv_ or na_:
            batch.cols = cols
    return batch


def encode_batch_framed(batch: TickBatch) -> bytes:
    """Flat encoding prefixed with a whole-frame CRC32 — the form the
    wire transports ship (loopback included, so every test run crosses
    the production framing)."""
    payload = encode_batch(batch)
    return _U32.pack(zlib.crc32(payload)) + payload


def decode_batch_framed(blob: bytes) -> TickBatch:
    """Verify the frame CRC, then decode.  Raises FrameCorruptError on
    any mismatch — a flipped bit anywhere in the frame is caught here,
    BEFORE record decoding can misinterpret corrupt lengths/ids."""
    if len(blob) < _U32.size:
        raise FrameCorruptError(f"frame too short ({len(blob)} bytes)")
    (crc,) = _U32.unpack_from(blob)
    payload = blob[_U32.size:]
    if zlib.crc32(payload) != crc:
        raise FrameCorruptError(
            f"frame CRC mismatch ({len(blob)} bytes)")
    return decode_batch(payload)
