"""TCP transport — the DCN peer message plane between hosts.

Replaces the reference's vendored `etcd/rafthttp` streams (reference
raft.go:170-184, 248-266) with persistent length-prefixed-frame TCP
connections carrying encoded TickBatches:

    frame := u32 payload_len | u32 src_node_id | payload(TickBatch codec)

Liveness model matches rafthttp's: outbound sends to unreachable peers are
dropped (raft re-sends every heartbeat tick), reconnection is automatic
with backoff, and only *local* listener failure is fatal — it surfaces via
on_error and tears the node down (reference raft.go:237-239).

Each peer gets a dedicated sender thread with a bounded queue so a slow or
dead peer can never stall the tick loop.  Accepted connections get TCP
keepalive, standing in for the reference's 3-minute keepalive period
(listener.go:55-57).

Robustness (PR 2 fault matrix):
  * payloads are CRC32-framed (codec.encode_batch_framed) — a frame
    corrupted anywhere between hosts is DROPPED and counted
    (NodeMetrics.faults_corrupt_frames via the `metrics` attribute the
    node wires in), and the recv loop keeps serving later frames;
  * any decode exception is confined to the frame: it can no longer
    kill the connection thread silently — the frame is skipped, the
    length-prefixed stream stays in sync, the listener stays alive;
  * `SendFaults` is the injectable send-side fault seam mirroring
    transport/faults.py's device-plane masks: seeded drop / corrupt /
    delay / one-directional block applied to encoded frames, so the
    chaos harness (chaos/scenarios.py) exercises THIS transport, not a
    stand-in.
"""
from __future__ import annotations

import logging
import queue
import socket
import struct
import threading
import time
from typing import Callable, Dict, List, Optional, Set, Tuple

import numpy as np

from raftsql_tpu.transport.base import TickBatch, Transport
from raftsql_tpu.transport.codec import (FrameCorruptError,
                                         decode_batch_framed,
                                         encode_batch_framed)

log = logging.getLogger("raftsql_tpu.tcp")

_FRAME = struct.Struct("<II")
_RECONNECT_S = 0.2
_QUEUE_CAP = 1024
# Upper bound on an inbound frame.  The u32 length field would otherwise
# let a corrupt or hostile peer make us buffer 4 GiB; a frame this large is
# never legitimate (batches are bounded by max_entries_per_msg per group),
# so the connection is dropped instead — the node itself must survive bad
# peers (see runtime/node.py _deliver).
_MAX_FRAME = 64 << 20


class SendFaults:
    """Seeded send-side fault injection for TcpTransport.

    The device plane's chaos masks (transport/faults.py) cannot reach
    this transport — frames leave through real sockets.  This seam
    applies the same fault classes to each ENCODED frame at send time:

      * one-directional blocks (`block`/`unblock`): frames to a blocked
        dst are dropped while the reverse direction flows — the
        asymmetric-partition failure mode;
      * seeded random drop (p_drop), corruption (p_corrupt — one byte
        of the framed payload is flipped, so the receiver's CRC check
        must catch and drop it), and delay (p_delay, delay_s — the
        frame is re-offered later from a timer thread, modeling
        out-of-order arrival).

    Thread-safe; all decisions come from one seeded rng so a given
    (seed, send sequence) reproduces the same fault pattern.
    """

    def __init__(self, seed: int = 0):
        self._rng = np.random.default_rng(seed)
        self._lock = threading.Lock()
        self._blocked: Set[int] = set()
        self.p_drop = 0.0
        self.p_corrupt = 0.0
        self.p_delay = 0.0
        self.delay_s = 0.0
        self.dropped = 0
        self.corrupted = 0
        self.delayed = 0

    def block(self, dst: int) -> None:
        """Stop delivering to node `dst` (1-based) — one direction only."""
        with self._lock:
            self._blocked.add(dst)

    def heal(self) -> None:
        with self._lock:
            self._blocked.clear()

    def set_rates(self, p_drop: float = 0.0, p_corrupt: float = 0.0,
                  p_delay: float = 0.0, delay_s: float = 0.0) -> None:
        with self._lock:
            self.p_drop = p_drop
            self.p_corrupt = p_corrupt
            self.p_delay = p_delay
            self.delay_s = delay_s

    def apply(self, dst: int, blob: bytes
              ) -> Optional[Tuple[bytes, float]]:
        """(possibly-mangled blob, delay_s) — or None to drop."""
        with self._lock:
            if dst in self._blocked:
                self.dropped += 1
                return None
            if self.p_drop and self._rng.random() < self.p_drop:
                self.dropped += 1
                return None
            if self.p_corrupt and self._rng.random() < self.p_corrupt:
                i = int(self._rng.integers(0, len(blob)))
                blob = blob[:i] + bytes([blob[i] ^ 0x5A]) + blob[i + 1:]
                self.corrupted += 1
            if self.p_delay and self._rng.random() < self.p_delay:
                self.delayed += 1
                return blob, self.delay_s
        return blob, 0.0


def parse_peer_url(url: str) -> Tuple[str, int]:
    """Accept the reference's peer URL form `http://host:port`
    (Procfile:2-4) or bare `host:port`."""
    hostport = url.split("://", 1)[-1].rstrip("/")
    host, port = hostport.rsplit(":", 1)
    return host, int(port)


class _PeerSender(threading.Thread):
    def __init__(self, src_id: int, addr: Tuple[str, int],
                 stop_evt: threading.Event):
        super().__init__(daemon=True, name=f"tcp-send-{addr[1]}")
        self.src_id = src_id
        self.addr = addr
        self.q: "queue.Queue[bytes]" = queue.Queue(maxsize=_QUEUE_CAP)
        self._stop = stop_evt
        self._sock: Optional[socket.socket] = None

    def offer(self, blob: bytes) -> None:
        try:
            self.q.put_nowait(blob)
        except queue.Full:        # drop-oldest: raft re-sends anyway
            try:
                self.q.get_nowait()
                self.q.put_nowait(blob)
            except queue.Empty:
                pass

    def _connect(self) -> Optional[socket.socket]:
        try:
            s = socket.create_connection(self.addr, timeout=1.0)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
            return s
        except OSError:
            return None

    def run(self) -> None:
        while not self._stop.is_set():
            try:
                blob = self.q.get(timeout=0.1)
            except queue.Empty:
                continue
            sent = False
            while not sent and not self._stop.is_set():
                if self._sock is None:
                    self._sock = self._connect()
                    if self._sock is None:
                        time.sleep(_RECONNECT_S)
                        # Peer down: drop this batch, drain stale queue.
                        break
                try:
                    self._sock.sendall(
                        _FRAME.pack(len(blob), self.src_id) + blob)
                    sent = True
                except OSError:
                    try:
                        self._sock.close()
                    finally:
                        self._sock = None
        if self._sock is not None:
            self._sock.close()


class TcpTransport(Transport):
    def __init__(self, peer_urls: List[str], self_index: int):
        """peer_urls[i] is node i+1's address (reference raft.go:148-151:
        node i serves at peers[i-1])."""
        self.addrs = [parse_peer_url(u) for u in peer_urls]
        self.self_index = self_index          # 0-based
        # Wired by the owning node (runtime/node.py start) so transport
        # fault counters land in the node's /metrics; a bare transport
        # (tests) counts into its own scratch NodeMetrics.
        from raftsql_tpu.utils.metrics import NodeMetrics
        self.metrics = NodeMetrics()
        # Injectable send-side fault seam (chaos harness); None in
        # production.
        self.faults: Optional[SendFaults] = None
        # Observability hook (raftsql_tpu/obs/ SpanTracer.note_event or
        # compatible), wired by the node's enable_tracing: frame
        # send/recv instants land on the host trace timeline.
        self.obs = None
        self._stop_evt = threading.Event()
        self._senders: Dict[int, _PeerSender] = {}
        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._conn_threads: List[threading.Thread] = []
        self._deliver: Callable[[int, TickBatch], None] = lambda s, b: None
        self._on_error: Callable[[Exception], None] = lambda e: None

    def start(self, node_id: int, deliver, on_error) -> None:
        self._deliver = deliver
        self._on_error = on_error
        host, port = self.addrs[self.self_index]
        try:
            ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            ls.bind((host if host not in ("localhost",) else "127.0.0.1",
                     port))
            ls.listen(16)
            ls.settimeout(0.2)
        except OSError as e:
            on_error(e)
            return
        self._listener = ls
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name=f"tcp-accept-{port}")
        self._accept_thread.start()
        for i, addr in enumerate(self.addrs):
            if i != self.self_index:
                s = _PeerSender(node_id, addr, self._stop_evt)
                s.start()
                self._senders[i + 1] = s

    def _accept_loop(self) -> None:
        assert self._listener is not None
        while not self._stop_evt.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError as e:
                if not self._stop_evt.is_set():
                    self._on_error(e)
                return
            conn.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
            t = threading.Thread(target=self._recv_loop, args=(conn,),
                                 daemon=True)
            t.start()
            self._conn_threads.append(t)

    def _recv_loop(self, conn: socket.socket) -> None:
        buf = b""
        conn.settimeout(0.5)
        try:
            while not self._stop_evt.is_set():
                while len(buf) >= _FRAME.size:
                    plen, src = _FRAME.unpack_from(buf)
                    if plen > _MAX_FRAME:
                        log.warning("dropping connection: oversized frame "
                                    "(%d bytes) from src %d", plen, src)
                        return
                    if len(buf) < _FRAME.size + plen:
                        break
                    payload = buf[_FRAME.size:_FRAME.size + plen]
                    buf = buf[_FRAME.size + plen:]
                    # A corrupt or malformed frame must cost exactly that
                    # frame: the length prefix already resynced the
                    # stream, so drop it, count it, keep receiving.
                    # Before this guard a decode exception killed the
                    # connection thread silently and every later frame
                    # with it.
                    try:
                        batch = decode_batch_framed(payload)
                    except (FrameCorruptError, struct.error,
                            ValueError) as e:
                        self.metrics.faults_corrupt_frames += 1
                        log.warning("dropping corrupt frame from src %d "
                                    "(%d bytes): %s", src, plen, e)
                        continue
                    if self.obs is not None:
                        self.obs.note_event("tcp.recv", src=src,
                                            n_bytes=plen)
                    self._deliver(src, batch)
                try:
                    chunk = conn.recv(1 << 16)
                except socket.timeout:
                    continue
                if not chunk:
                    return
                buf += chunk
        except OSError:
            pass
        finally:
            conn.close()

    def send(self, dst: int, batch: TickBatch) -> None:
        if batch.empty():
            return
        sender = self._senders.get(dst)
        if sender is None:
            return
        blob = encode_batch_framed(batch)
        if self.obs is not None:
            self.obs.note_event("tcp.send", dst=dst, n_bytes=len(blob))
        if self.faults is not None:
            got = self.faults.apply(dst, blob)
            if got is None:
                return                       # injected drop / block
            blob, delay = got
            if delay > 0:
                t = threading.Timer(delay, sender.offer, args=(blob,))
                t.daemon = True
                t.start()
                return
        sender.offer(blob)

    def stop(self) -> None:
        self._stop_evt.set()
        if self._listener is not None:
            self._listener.close()
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=2)
