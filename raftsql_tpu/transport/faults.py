"""Fault injection for the batched message plane.

The reference's only fault story is test-driven node stop/restart
(reference raftsql_test.go:47-52, 117-170) — SURVEY.md §5.3 calls for
injectable message drop/delay in the batched transport.  Because messages
here are dense arrays, faults are *masks*: dropping a message zeroes its
type code; partitioning a peer zeroes every slot to and from it.  The same
masks work on a live `Inbox` between ticks (host-side chaos) and inside a
jitted schedule (deterministic simulated-time property tests).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from raftsql_tpu.core.state import Inbox


def drop_messages(inbox: Inbox, drop: jax.Array) -> Inbox:
    """Drop messages by mask.

    Args:
      inbox: stacked cluster inbox, leaves [P_dst, G, P_src, ...] (or a
        single peer's inbox [G, P_src, ...]).
      drop: bool mask broadcastable to [P_dst, G, P_src] (resp. [G, P_src]);
        True = the message in that slot is lost.
    """
    keep = ~drop

    def mask(x):
        m = keep
        while m.ndim < x.ndim:
            m = m[..., None]
        return jnp.where(m, x, jnp.zeros_like(x))

    return jax.tree.map(mask, inbox)


def random_drop(inbox: Inbox, key: jax.Array, p_drop: float) -> Inbox:
    """Drop each message slot independently with probability p_drop."""
    shape = inbox.v_type.shape  # [..., G, P_src]
    drop = jax.random.bernoulli(key, p_drop, shape)
    return drop_messages(inbox, drop)


def partition_peer(inbox: Inbox, peer: int | jax.Array) -> Inbox:
    """Isolate one peer of a stacked cluster inbox: nothing in, nothing out.

    inbox leaves are [P_dst, G, P_src, ...]; we zero row dst==peer and
    column src==peer, which is exactly a network partition of that peer in
    the reference's rafthttp topology (reference raft.go:180-184).
    """
    P = inbox.v_type.shape[0]
    dst = jnp.arange(P) == peer            # [P]
    src = jnp.arange(P) == peer            # [P]
    drop = dst[:, None, None] | src[None, None, :]   # [P, 1, P]
    drop = jnp.broadcast_to(drop, inbox.v_type.shape)
    return drop_messages(inbox, drop)
