"""Fault injection for the batched message plane.

The reference's only fault story is test-driven node stop/restart
(reference raftsql_test.go:47-52, 117-170) — SURVEY.md §5.3 calls for
injectable message drop/delay in the batched transport.  Because messages
here are dense arrays, faults are *masks*: dropping a message zeroes its
type code; partitioning a peer zeroes every slot to and from it.  The same
masks work on a live `Inbox` between ticks (host-side chaos) and inside a
jitted schedule (deterministic simulated-time property tests).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from raftsql_tpu.core.state import Inbox


def drop_messages(inbox: Inbox, drop: jax.Array) -> Inbox:
    """Drop messages by mask.

    Args:
      inbox: stacked cluster inbox, leaves [P_dst, G, P_src, ...] (or a
        single peer's inbox [G, P_src, ...]).
      drop: bool mask broadcastable to [P_dst, G, P_src] (resp. [G, P_src]);
        True = the message in that slot is lost.
    """
    keep = ~drop

    def mask(x):
        m = keep
        while m.ndim < x.ndim:
            m = m[..., None]
        return jnp.where(m, x, jnp.zeros_like(x))

    return jax.tree.map(mask, inbox)


def random_drop(inbox: Inbox, key: jax.Array, p_drop: float) -> Inbox:
    """Drop each message slot independently with probability p_drop."""
    shape = inbox.v_type.shape  # [..., G, P_src]
    drop = jax.random.bernoulli(key, p_drop, shape)
    return drop_messages(inbox, drop)


def hold_messages(inbox: Inbox, hold: jax.Array) -> tuple[Inbox, Inbox]:
    """Split an inbox for DELAY injection: (delivered, held).

    `delivered` is the inbox with the held slots zeroed (they do not
    arrive this tick); `held` contains ONLY the held slots (everything
    else zeroed), to be re-injected into a later tick's inbox with
    `release_messages`.  Mask semantics match `drop_messages` (True =
    this slot is delayed).
    """
    return drop_messages(inbox, hold), drop_messages(inbox, ~hold)


def release_messages(inbox: Inbox, held: Inbox) -> Inbox:
    """Overlay previously-held message slots into a live inbox.

    A held slot wins where it actually carries a message (nonzero type
    code); per-slot the vote plane and the append plane overlay
    independently, mirroring the dense Inbox's two-slot schema.  Any
    same-slot message composed this tick is overwritten — the standard
    overwrite-newest slot semantics, with "newest" being the delayed
    delivery (raft tolerates both loss and reordering, so this is a
    legal adversarial schedule).
    """
    v_m = held.v_type != 0          # [.., G, P_src]
    a_m = held.a_type != 0

    def overlay(name: str, live: jax.Array, hld: jax.Array) -> jax.Array:
        m = v_m if name.startswith("v_") else a_m
        while m.ndim < live.ndim:
            m = m[..., None]
        return jnp.where(m, hld, live)

    return Inbox(*[overlay(n, getattr(inbox, n), getattr(held, n))
                   for n in Inbox._fields])


def asym_partition(inbox: Inbox, src: int | jax.Array,
                   dst: int | jax.Array) -> Inbox:
    """One-directional partition of a stacked cluster inbox: `dst` stops
    hearing `src`, while `src` still hears `dst`.

    This is the half-open failure mode a full isolation cannot express
    (a one-way firewall rule, a dead NIC receive queue): the deaf side
    keeps timing out and probing while the other side believes the link
    is healthy — exactly the schedule where prevote's lease check and
    the term-bump rules earn their keep ("Paxos vs Raft" §4's
    asymmetric-partition liveness scenarios).  inbox leaves are
    [P_dst, G, P_src, ...]; we zero only row dst == `dst`, column
    src == `src`.
    """
    P = inbox.v_type.shape[0]
    dmask = (jnp.arange(P) == dst)[:, None, None]     # [P, 1, 1]
    smask = (jnp.arange(P) == src)[None, None, :]     # [1, 1, P]
    drop = jnp.broadcast_to(dmask & smask, inbox.v_type.shape)
    return drop_messages(inbox, drop)


def partition_peer(inbox: Inbox, peer: int | jax.Array) -> Inbox:
    """Isolate one peer of a stacked cluster inbox: nothing in, nothing out.

    inbox leaves are [P_dst, G, P_src, ...]; we zero row dst==peer and
    column src==peer, which is exactly a network partition of that peer in
    the reference's rafthttp topology (reference raft.go:180-184).
    """
    P = inbox.v_type.shape[0]
    dst = jnp.arange(P) == peer            # [P]
    src = jnp.arange(P) == peer            # [P]
    drop = dst[:, None, None] | src[None, None, :]   # [P, 1, P]
    drop = jnp.broadcast_to(drop, inbox.v_type.shape)
    return drop_messages(inbox, drop)
