"""In-process transport: the reference test harness's localhost cluster
(reference raftsql_test.go:16-28) without sockets.

Batches still round-trip through the binary codec so every test exercises
the real wire format.  Delivery is synchronous on the sender's thread into
the receiver's staging (RaftNode.deliver is non-blocking: it only appends
to staging dicts under a lock).

A `FaultPlan` may drop batches between specific nodes — the host-plane
counterpart of transport.faults for the device plane.
"""
from __future__ import annotations

import struct
import threading
from typing import Callable, Dict, Optional, Set, Tuple

from raftsql_tpu.transport.base import TickBatch, Transport
from raftsql_tpu.transport.codec import (FrameCorruptError,
                                         decode_batch_framed,
                                         encode_batch_framed)


class FaultPlan:
    """Mutable set of blocked (src, dst) node pairs."""

    def __init__(self):
        self._blocked: Set[Tuple[int, int]] = set()
        self._lock = threading.Lock()

    def isolate(self, node: int, universe: range) -> None:
        with self._lock:
            for other in universe:
                self._blocked.add((node, other))
                self._blocked.add((other, node))

    def block(self, src: int, dst: int) -> None:
        """Block ONE direction: dst stops hearing src while src still
        hears dst — the asymmetric-partition failure mode (a dead NIC
        queue, a one-way firewall rule) the chaos matrix schedules."""
        with self._lock:
            self._blocked.add((src, dst))

    def heal(self) -> None:
        with self._lock:
            self._blocked.clear()

    def blocked(self, src: int, dst: int) -> bool:
        with self._lock:
            return (src, dst) in self._blocked


class LoopbackHub:
    """Shared registry wiring N LoopbackTransports together.

    codec=False skips the encode/decode round trip and hands the
    TickBatch object across directly — for benchmarks that measure the
    engine rather than the wire format (tests keep the default True so
    every suite run exercises the real codec)."""

    def __init__(self, faults: Optional[FaultPlan] = None,
                 codec: bool = True):
        self._nodes: Dict[int, Callable[[int, TickBatch], None]] = {}
        self._lock = threading.Lock()
        self.faults = faults or FaultPlan()
        self.codec = codec
        # Wire-corruption seam (chaos harness): a callable
        # (src, dst, blob) -> blob mutating the encoded frame in
        # flight.  The CRC framing then catches the damage at decode
        # and the frame is dropped + counted, exactly as on the TCP
        # path.  None in normal runs.
        self.mangler: Optional[Callable[[int, int, bytes], bytes]] = None
        # Corrupt frames dropped by the CRC check, and an optional
        # per-drop callback (the chaos runner uses it to charge the
        # receiving node's NodeMetrics.faults_corrupt_frames).
        self.corrupt_dropped = 0
        self.on_corrupt: Optional[Callable[[int, int], None]] = None

    def attach(self, node_id: int,
               deliver: Callable[[int, TickBatch], None]) -> None:
        with self._lock:
            self._nodes[node_id] = deliver

    def detach(self, node_id: int) -> None:
        with self._lock:
            self._nodes.pop(node_id, None)

    def route(self, src: int, dst: int, batch) -> None:
        """`batch` is encoded bytes (codec=True) or a TickBatch object."""
        if self.faults.blocked(src, dst):
            return
        with self._lock:
            deliver = self._nodes.get(dst)
        if deliver is None:                # absent peer == dropped message
            return
        if self.codec:
            if self.mangler is not None:
                batch = self.mangler(src, dst, batch)
            try:
                batch = decode_batch_framed(batch)
            except (FrameCorruptError, ValueError, struct.error):
                with self._lock:
                    self.corrupt_dropped += 1
                if self.on_corrupt is not None:
                    self.on_corrupt(src, dst)
                return
        deliver(src, batch)


class LoopbackTransport(Transport):
    def __init__(self, hub: LoopbackHub):
        self.hub = hub
        self.node_id = -1

    def start(self, node_id: int,
              deliver: Callable[[int, TickBatch], None],
              on_error: Callable[[Exception], None]) -> None:
        self.node_id = node_id
        self.hub.attach(node_id, deliver)

    def send(self, dst: int, batch: TickBatch) -> None:
        if batch.empty():
            return
        self.hub.route(self.node_id, dst,
                       encode_batch_framed(batch) if self.hub.codec
                       else batch)

    def stop(self) -> None:
        self.hub.detach(self.node_id)
