"""Transport protocol + wire records for the peer message plane.

This is the TPU-native replacement for the reference's vendored
`etcd/rafthttp` transport (reference raft.go:170-184, 230, 248-273):
per-tick *batches* of fixed-layout records move between nodes, instead of a
stream of protobuf messages.  Three implementations share this interface:

  - transport.loopback — in-process, for tests and single-host clusters
    (the reference test harness's localhost trick, raftsql_test.go:19);
  - transport.tcp      — DCN path between hosts, length-prefixed frames
    over persistent sockets;
  - the fused on-device path (core/cluster.deliver) needs no transport at
    all — delivery is an array transpose (and an ICI all_to_all when the
    peer axis is sharded, parallel/sharded.py).

Wire records mirror the dense Inbox slots (core/state.py) one-to-one, so
staging inbound records into device arrays is a plain scatter.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, List, Protocol


@dataclass
class VoteRec:
    group: int
    type: int           # MSG_REQ / MSG_RESP / MSG_PREREQ / MSG_PRERESP
    term: int
    last_idx: int = 0   # request fields
    last_term: int = 0
    granted: bool = False  # response field


@dataclass
class AppendRec:
    group: int
    type: int           # MSG_REQ / MSG_RESP
    term: int
    prev_idx: int = 0
    prev_term: int = 0
    ent_terms: List[int] = field(default_factory=list)
    payloads: List[bytes] = field(default_factory=list)   # REQ only
    commit: int = 0
    success: bool = False   # response fields
    match: int = 0
    # Round binding for ReadIndex (raft §6.4): a REQ carries the sender's
    # tick number; the RESP echoes the seq of the request it answers, so
    # a leadership confirmation can be tied to rounds STARTED after a
    # read registration (a delayed pre-registration response must not
    # count — runtime/node.py read_ready).
    seq: int = 0

    @property
    def n(self) -> int:
        return len(self.ent_terms)


@dataclass
class ProposalRec:
    """Host-level proposal forward to the (hinted) leader.

    The reference gets leader forwarding for free from etcd/raft's MsgProp
    routing; here it is an explicit host-plane record.
    """
    group: int
    payload: bytes


@dataclass
class SnapshotRec:
    """InstallSnapshot: full state transfer for a follower whose needed
    log prefix has been compacted away (raft §7; the reference has no
    snapshots at all, db.go:27-29 — this is capability beyond parity).

    `blob` is the state machine's serialized image at `last_idx` (whose
    entry has term `last_term`); the receiver installs it, resets its
    group log to start at last_idx, and resumes replication from there.
    """
    group: int
    last_idx: int
    last_term: int
    term: int           # sender's (leader's) current term
    blob: bytes = b""


@dataclass
class ColRecs:
    """Columnar (struct-of-arrays) payload-free messages — the host-plane
    fast path.

    Per-record Python objects dominate the durable tick at scale: every
    leader group emits P-1 heartbeat appends per heartbeat tick and every
    follower answers each, so message count is O(G) regardless of load
    (~20-40 µs of build+stage Python per record).  Votes and payload-free
    appends (heartbeats, all responses) instead ride as parallel numpy
    int column arrays: the sender fancy-indexes them straight out of the
    device outbox, the receiver scatters them straight into its staging
    arrays, and the wire format is the raw little-endian array bytes
    (codec.py).  Payload-carrying appends, proposals, and snapshots keep
    the record path — their count is proportional to real traffic.

    This is SURVEY.md §2b V2's struct-of-arrays wire contract applied to
    the host plane end-to-end, not just the device boundary.
    """
    # Vote rows (all vote messages):
    v_group: "object" = None    # np.ndarray [Nv] i32
    v_type: "object" = None
    v_term: "object" = None
    v_last_idx: "object" = None
    v_last_term: "object" = None
    v_granted: "object" = None  # i32 0/1
    # Payload-free append rows (n == 0: heartbeats + responses):
    a_group: "object" = None    # np.ndarray [Na] i32
    a_type: "object" = None
    a_term: "object" = None
    a_prev_idx: "object" = None
    a_prev_term: "object" = None
    a_commit: "object" = None
    a_success: "object" = None  # i32 0/1
    a_match: "object" = None
    a_seq: "object" = None      # i64 (ReadIndex round binding)

    def n_votes(self) -> int:
        return 0 if self.v_group is None else len(self.v_group)

    def n_appends(self) -> int:
        return 0 if self.a_group is None else len(self.a_group)


@dataclass
class TickBatch:
    """Everything one node sends another for one tick."""
    votes: List[VoteRec] = field(default_factory=list)
    appends: List[AppendRec] = field(default_factory=list)
    proposals: List[ProposalRec] = field(default_factory=list)
    snapshots: List[SnapshotRec] = field(default_factory=list)
    cols: "ColRecs | None" = None

    def empty(self) -> bool:
        return not (self.votes or self.appends or self.proposals
                    or self.snapshots
                    or (self.cols is not None
                        and (self.cols.n_votes() or self.cols.n_appends())))


class Transport(Protocol):
    """Peer message plane for one node.

    `send` must not block the tick loop on slow peers (drop or buffer);
    raft tolerates loss.  Fatal transport errors surface via the error
    callback, which triggers node teardown (reference raft.go:136-142,
    237-239).
    """

    def start(self, node_id: int,
              deliver: Callable[[int, TickBatch], None],
              on_error: Callable[[Exception], None]) -> None: ...

    def send(self, dst: int, batch: TickBatch) -> None: ...

    def stop(self) -> None: ...
