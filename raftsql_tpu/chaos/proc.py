"""Process-plane chaos: a seeded nemesis over REAL server processes.

The harness's other runners (chaos/scenarios.py) stop at in-process
runtimes; ROADMAP lists "multi-process chaos (real SIGKILL of server
processes)" as the last open chaos frontier, and the consensus-testing
literature (arXiv:2004.05074, arXiv:1905.10786) locates exactly the
bugs in-process simulation cannot reach: real SIGKILL timing against a
kernel-scheduled tick thread, stalled-but-not-dead processes, and
clients retrying writes across leader failure.  This module drives a
real N-process cluster (server/main.py children, TcpTransport between
them, HTTP on top) through a seeded `ProcChaosPlan`:

  * SIGKILL crashes — leader-targeted (resolved live via /healthz) and
    random — with respawn on the SAME ports and data dirs;
  * SIGSTOP/SIGCONT stalls — the GC-pause / VM-freeze failure mode: a
    frozen leader must be deposed and rejoin as a follower, with every
    write acked before the stall intact;
  * rolling-restart storms — clean SIGTERM stops (the graceful-shutdown
    path) with immediate same-port rebinds, one node at a time;
  * env-injected storage faults — RAFTSQL_FSIO_FAULTS specs
    (storage/fsio.py) give children ENOSPC at a chosen WAL write and a
    hard process exit at a chosen WAL fsync, so torn-tail and
    epoch-repair recovery runs in real processes.

A workload of acked PUTs (via the hardened api/client.py, whose retry
tokens make re-sends across crashes exactly-once) feeds the ledger;
live /healthz polling feeds the single-leader invariant; after the
heal window the survivors must CONVERGE (identical rows everywhere, a
superset of every acked write, each acked write exactly once), and a
post-mortem replays every surviving WAL dir and re-opens every SQLite
DB to re-prove durability from disk alone.

Determinism contract (the WEAKEST in the harness, documented in the
README fault matrix): the SCHEDULE is a pure function of the seed and
the invariant VERDICTS must reproduce — `make chaos-procs` runs one
seed twice and compares schedule + verdict digests — but the committed
history crosses three kernels' schedulers and is not bit-reproducible.
On any invariant failure the runner dumps a flight bundle
(per-process log tails, /metrics, /trace, WAL dir listings) via
obs/flight.py before re-raising.
"""
from __future__ import annotations

import hashlib
import json
import os
import signal
import sqlite3
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Set

import numpy as np

from raftsql_tpu.api.client import RaftSQLClient, SQLError, Unavailable
from raftsql_tpu.chaos.invariants import (ElectionSafety,
                                          InvariantViolation,
                                          RegisterLinearizability)
from raftsql_tpu.chaos.schedule import LEADER_TARGET, ProcChaosPlan
from raftsql_tpu.storage.fsio import EXIT_CODE_FSYNC_CRASH

# server/main.py EXIT_CODE_FATAL without importing the server module
# (it pulls the whole engine; the nemesis stays engine-import-free so
# it can babysit children that ARE the engine).
EXIT_CODE_FATAL = 70

_LEADER = "leader"


def _reserve_ports(n: int):
    import socket
    socks = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


class ProcCluster:
    """N `server/main.py` OS processes on localhost — the Procfile
    topology under nemesis control.  SIGTERM is "clean stop" (the
    graceful-shutdown handler flushes the WAL and exits 0); SIGKILL is
    "crash"; SIGSTOP/SIGCONT is "stall"."""

    def __init__(self, workdir: str, peers: int = 3, groups: int = 1,
                 tick: float = 0.02, http_engine: str = "aio"):
        self.workdir = str(workdir)
        self.peers = peers
        self.groups = groups
        self.tick = tick
        self.http_engine = http_engine
        ports = _reserve_ports(2 * peers)
        self.peer_ports, self.http_ports = ports[:peers], ports[peers:]
        self.cluster = ",".join(f"http://127.0.0.1:{p}"
                                for p in self.peer_ports)
        self.procs: List[Optional[subprocess.Popen]] = [None] * peers
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        self.env_base = dict(
            os.environ, JAX_PLATFORMS="cpu",
            PYTHONPATH=repo_root + (
                os.pathsep + os.environ["PYTHONPATH"]
                if os.environ.get("PYTHONPATH") else ""))
        self.env_base.pop("RAFTSQL_FSIO_FAULTS", None)
        os.makedirs(self.workdir, exist_ok=True)

    def spawn(self, i: int, fsio_spec: Optional[str] = None) -> None:
        """(Re)spawn peer i — same ports, same data dir, WAL replay.
        `fsio_spec` rides RAFTSQL_FSIO_FAULTS into the child."""
        assert self.procs[i] is None or self.procs[i].poll() is not None
        env = dict(self.env_base)
        if fsio_spec:
            env["RAFTSQL_FSIO_FAULTS"] = fsio_spec
        logf = open(os.path.join(self.workdir, f"node{i + 1}.log"), "ab")
        self.procs[i] = subprocess.Popen(
            [sys.executable, "-m", "raftsql_tpu.server.main",
             "--id", str(i + 1), "--cluster", self.cluster,
             "--port", str(self.http_ports[i]),
             "--tick", str(self.tick), "--groups", str(self.groups),
             "--http-engine", self.http_engine],
            cwd=self.workdir, env=env, stdout=logf, stderr=logf)
        logf.close()      # child inherited the fd

    def alive(self, i: int) -> bool:
        p = self.procs[i]
        return p is not None and p.poll() is None

    def exit_code(self, i: int) -> Optional[int]:
        """Exit code if peer i's process has died, else None."""
        p = self.procs[i]
        if p is None:
            return None
        return p.poll()

    def sigkill(self, i: int) -> None:
        p = self.procs[i]
        if p is not None and p.poll() is None:
            p.send_signal(signal.SIGKILL)
            p.wait(timeout=15)

    def sigterm(self, i: int, timeout: float = 15.0) -> Optional[int]:
        """Clean stop; returns the exit code (0 = graceful)."""
        p = self.procs[i]
        if p is None:
            return None
        if p.poll() is None:
            p.terminate()
            try:
                p.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait(timeout=10)
        return p.returncode

    def sigstop(self, i: int) -> None:
        p = self.procs[i]
        if p is not None and p.poll() is None:
            p.send_signal(signal.SIGSTOP)

    def sigcont(self, i: int) -> None:
        p = self.procs[i]
        if p is not None and p.poll() is None:
            p.send_signal(signal.SIGCONT)

    def stop_all(self) -> List[Optional[int]]:
        codes = []
        for i in range(self.peers):
            p = self.procs[i]
            if p is not None and p.poll() is None:
                p.send_signal(signal.SIGCONT)   # a stalled child first
        for i in range(self.peers):
            codes.append(self.sigterm(i))
        return codes

    def data_dir(self, i: int) -> str:
        return os.path.join(self.workdir, f"raftsql-{i + 1}")

    def db_path(self, i: int) -> str:
        return os.path.join(self.workdir, f"raftsql-{i + 1}.db")

    def log_tail(self, i: int, nbytes: int = 4096) -> str:
        path = os.path.join(self.workdir, f"node{i + 1}.log")
        try:
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                f.seek(max(0, f.tell() - nbytes))
                return f.read().decode("utf-8", "replace")
        except OSError:
            return ""


class ProcChaosRunner:
    """Drive a ProcChaosPlan against a real cluster; see module doc."""

    def __init__(self, plan: ProcChaosPlan, workdir: str,
                 http_engine: str = "aio"):
        self.plan = plan
        self.cluster = ProcCluster(workdir, peers=plan.peers,
                                   groups=plan.groups,
                                   http_engine=http_engine)
        self.client = RaftSQLClient(
            [f"127.0.0.1:{p}" for p in self.cluster.http_ports],
            timeout_s=3.0)
        self.safety = ElectionSafety()
        self.acked: List[str] = []           # ledger: values acked 204
        self._acked_lock = threading.Lock()
        self._stop_workload = threading.Event()
        self._workload_err: Optional[BaseException] = None
        # peer -> tick at which to respawn; peer -> stalled flag.
        self._down_until: Dict[int, int] = {}
        self._stalled: Set[int] = set()
        self.report = {
            "kills": 0, "stalls": 0, "storm_restarts": 0,
            "respawns": 0, "fsio_exits": 0, "fatal_exits": 0,
            "unexpected_exits": 0, "acked": 0, "graceful_stops": 0,
        }
        self.verdicts: Dict[str, str] = {}

    # -- workload ------------------------------------------------------

    def _workload(self) -> None:
        """Acked-PUT feed: unique values, one retry token per value, so
        every 204 is a durability promise the post-mortem can hold the
        cluster to.  Engine-death 400s and deadline misses leave the
        value UNACKED (no promise) and move on."""
        n = 0
        while not self._stop_workload.is_set():
            val = f"w{n}"
            n += 1
            try:
                self.client.put(
                    f"INSERT INTO chaos (v) VALUES ('{val}')",
                    deadline_s=8.0)
                with self._acked_lock:
                    self.acked.append(val)
            except (SQLError, Unavailable):
                pass
            except BaseException as e:       # noqa: BLE001 - surfaced
                self._workload_err = e
                return
            time.sleep(0.08)

    # -- nemesis helpers -----------------------------------------------

    def _healthz_all(self) -> Dict[int, Optional[dict]]:
        docs: Dict[int, Optional[dict]] = {}
        for i in range(self.plan.peers):
            if not self.cluster.alive(i) or i in self._stalled:
                docs[i] = None
            else:
                docs[i] = self.client.health(i, timeout_s=1.0)
        return docs

    def _resolve(self, peer: int, docs: Dict[int, Optional[dict]]) -> int:
        """LEADER_TARGET → whoever reports role=leader for group 0 (a
        live node's own view wins; fall back to any live node's leader
        hint, then to the lowest live peer)."""
        if peer != LEADER_TARGET:
            return peer
        for i, doc in sorted(docs.items()):
            if doc and doc["groups"].get("0", {}).get("role") == _LEADER:
                return i
        for i, doc in sorted(docs.items()):
            if doc:
                lead = int(doc["groups"].get("0", {}).get("leader", 0))
                if lead > 0:
                    return lead - 1
        for i in range(self.plan.peers):
            if self.cluster.alive(i) and i not in self._stalled:
                return i
        return 0

    def _observe(self, t: int, docs: Dict[int, Optional[dict]]) -> None:
        """Feed /healthz snapshots to the single-leader invariant.
        Commit monotonicity is NOT asserted on this plane: /healthz
        reads the live cache, and a SIGKILL may legally roll an
        observed-but-unsynced commit index back to the WAL's."""
        P, G = self.plan.peers, self.plan.groups
        roles = np.full((P, G), -1, np.int64)
        terms = np.zeros((P, G), np.int64)
        code = {"follower": 0, "candidate": 1, _LEADER: 2,
                "precandidate": 3}
        for i, doc in docs.items():
            if not doc:
                continue
            for g in range(G):
                row = doc["groups"].get(str(g))
                if row:
                    roles[i, g] = code.get(row.get("role"), -1)
                    terms[i, g] = int(row.get("term", 0))
        self.safety.observe(t, roles, terms)

    def _handle_exits(self, t: int) -> None:
        """Unscheduled child deaths: injected crash points (exit 86),
        fatal-posture engine deaths (exit 70, e.g. injected ENOSPC),
        or a real bug (anything else — still respawned, but counted
        separately so the gate can flag it)."""
        for i in range(self.plan.peers):
            if i in self._down_until or i in self._stalled:
                continue
            code = self.cluster.exit_code(i)
            if code is None:
                continue
            if code == EXIT_CODE_FSYNC_CRASH:
                self.report["fsio_exits"] += 1
            elif code == EXIT_CODE_FATAL:
                self.report["fatal_exits"] += 1
            else:
                self.report["unexpected_exits"] += 1
            self._down_until[i] = t + 2      # the operator reacts fast

    def _respawn_due(self, t: int) -> None:
        for i in [i for i, d in self._down_until.items() if d <= t]:
            del self._down_until[i]
            # Faulted env specs are first-boot only: the crash point
            # fired, the disk "recovered", the respawn runs clean.
            self.cluster.spawn(i)
            self.report["respawns"] += 1

    # -- phases --------------------------------------------------------

    def _boot(self) -> None:
        """Spawn everyone (with their env fault specs) and wait healthy.
        A low-threshold env fault may fire DURING boot — the first
        election's hard-state writes count too — so a child death here
        is scored like any other and the peer is respawned clean."""
        spec_of = {f.peer: f.spec for f in self.plan.fsio}
        for i in range(self.plan.peers):
            self.cluster.spawn(i, fsio_spec=spec_of.get(i))
        deadline = time.monotonic() + 90.0
        pending = set(range(self.plan.peers))
        while pending:
            if time.monotonic() > deadline:
                raise Unavailable(
                    f"nodes {sorted(pending)} never became healthy")
            for i in sorted(pending):
                code = self.cluster.exit_code(i)
                if code is not None:
                    if code == EXIT_CODE_FSYNC_CRASH:
                        self.report["fsio_exits"] += 1
                    elif code == EXIT_CODE_FATAL:
                        self.report["fatal_exits"] += 1
                    else:
                        self.report["unexpected_exits"] += 1
                    self.cluster.spawn(i)
                    self.report["respawns"] += 1
                    continue
                if self.client.health(i) is not None:
                    pending.discard(i)
            time.sleep(0.3)
        # Idempotent so a cross-call retry (fresh token) after an
        # engine-death 400 cannot fail on its own success.
        create_deadline = time.monotonic() + 60.0
        while True:
            try:
                self.client.put(
                    "CREATE TABLE IF NOT EXISTS chaos (v text)",
                    deadline_s=15.0)
                return
            except (SQLError, Unavailable):
                if time.monotonic() > create_deadline:
                    raise
                time.sleep(0.5)

    def _usable(self, p: int) -> bool:
        return self.cluster.alive(p) and p not in self._stalled \
            and p not in self._down_until

    def _script(self) -> None:
        """Run the scripted phase.  Events are DUE at their tick but
        DEFERRED — not dropped — while their target cannot take the
        fault (already dead of an injected disk fault, mid-respawn, or
        stalled): a nemesis that silently skips a scheduled kill makes
        the fired-families verdict a coin flip.  The script runs past
        plan.ticks (bounded) until every event has fired."""
        plan = self.plan
        kills = sorted(plan.kills, key=lambda k: k.tick)
        stalls = sorted(plan.stalls, key=lambda s: s.tick)
        storm_jobs = sorted(
            (storm.tick + k * storm.gap, k)
            for storm in plan.storms for k in range(plan.peers))
        cont_at: Dict[int, int] = {}        # tick -> peer to SIGCONT
        max_script = plan.ticks + 80
        t = 0
        while True:
            docs = self._healthz_all()
            self._observe(t, docs)
            for k in list(kills):
                if k.tick > t:
                    break
                p = self._resolve(k.peer, docs)
                if self._usable(p):
                    self.cluster.sigkill(p)
                    self._down_until[p] = t + k.down
                    self.report["kills"] += 1
                    kills.remove(k)
            for s in list(stalls):
                if s.tick > t:
                    break
                p = self._resolve(s.peer, docs)
                if self._usable(p):
                    self.cluster.sigstop(p)
                    self._stalled.add(p)
                    cont_at[t + s.ticks] = p
                    self.report["stalls"] += 1
                    stalls.remove(s)
            for (due, p) in list(storm_jobs):
                if due > t:
                    break
                if self._usable(p):
                    code = self.cluster.sigterm(p)
                    if code == 0:
                        self.report["graceful_stops"] += 1
                    self.cluster.spawn(p)   # immediate same-port rebind
                    self.report["storm_restarts"] += 1
                    storm_jobs.remove((due, p))
            p = cont_at.pop(t, None)
            if p is not None:
                self.cluster.sigcont(p)
                self._stalled.discard(p)
            self._handle_exits(t)
            self._respawn_due(t)
            time.sleep(plan.tick_s)
            if self._workload_err is not None:
                raise self._workload_err
            t += 1
            pending = kills or stalls or storm_jobs or cont_at
            if (t >= plan.ticks and not pending) or t >= max_script:
                break
        # End of script: everyone up and running for the heal window.
        for p in list(self._stalled):
            self.cluster.sigcont(p)
            self._stalled.discard(p)
        for i in list(self._down_until):
            del self._down_until[i]
            self.cluster.spawn(i)
            self.report["respawns"] += 1
        self._handle_exits(t)
        self._respawn_due(t + 3)
        for h in range(plan.heal_ticks):
            docs = self._healthz_all()
            self._observe(t + 1 + h, docs)
            self._handle_exits(t + 1 + h)
            self._respawn_due(t + 1 + h)
            time.sleep(plan.tick_s)

    def _converge(self, deadline_s: float = 60.0) -> List[str]:
        """Every node must answer the full ordered table identically,
        covering every acked write exactly once.  Returns the rows."""
        with self._acked_lock:
            acked = list(self.acked)
        want_rows = {f"|{v}|" for v in acked}
        deadline = time.monotonic() + deadline_s
        last: object = None
        query = "SELECT v FROM chaos ORDER BY v"
        while time.monotonic() < deadline:
            answers = []
            try:
                for i in range(self.plan.peers):
                    answers.append(self.client.get(
                        query, node=i, deadline_s=10.0))
            except (Unavailable, SQLError) as e:
                last = e
                time.sleep(0.5)
                continue
            rows = answers[0].splitlines()
            if all(a == answers[0] for a in answers) \
                    and want_rows.issubset(rows):
                dup = [v for v in acked if rows.count(f"|{v}|") != 1]
                if dup:
                    raise InvariantViolation(
                        f"exactly-once violated: acked values applied "
                        f"more than once: {dup[:5]} "
                        f"(of {len(dup)})")
                return rows
            last = [len(a.splitlines()) for a in answers]
            time.sleep(0.5)
        raise InvariantViolation(
            f"survivors failed to converge on {len(acked)} acked "
            f"writes before the deadline; last={last!r}")

    def _post_mortem(self) -> None:
        """Durability from DISK alone: replay every node's WAL dir and
        re-open every SQLite DB after the graceful stop — every acked
        write must be in every node's committed WAL prefix (exactly
        once, post-dedup) and in every rebuilt SQLite table."""
        from raftsql_tpu.runtime.envelope import unwrap
        from raftsql_tpu.storage.wal import WAL
        with self._acked_lock:
            acked = list(self.acked)
        for i in range(self.plan.peers):
            groups = WAL.replay(self.cluster.data_dir(i))
            gl = groups.get(0)
            if gl is None:
                raise InvariantViolation(
                    f"node {i + 1}: WAL replay has no group 0")
            committed = gl.entries[:max(0, gl.hard.commit - gl.start)]
            seen_pids: Set[int] = set()
            values: List[str] = []
            for (_term, data) in committed:
                if not data:
                    continue
                pid, payload = unwrap(data)
                if pid is not None:
                    if pid in seen_pids:
                        continue             # retry duplicate: one apply
                    seen_pids.add(pid)
                sql = payload.decode("utf-8", "replace")
                if "VALUES ('" in sql:
                    values.append(sql.split("('", 1)[1].split("')")[0])
            missing = [v for v in acked if v not in set(values)]
            if missing:
                raise InvariantViolation(
                    f"node {i + 1}: {len(missing)} acked writes missing "
                    f"from the committed WAL prefix, e.g. {missing[:5]}")
            dups = {v for v in acked if values.count(v) != 1}
            if dups:
                raise InvariantViolation(
                    f"node {i + 1}: acked writes applied more than once "
                    f"in the WAL apply stream: {sorted(dups)[:5]}")
            # The SQLite file the stopped process left behind IS the
            # applied state — read it cold.
            conn = sqlite3.connect(self.cluster.db_path(i))
            try:
                rows = [r[0] for r in conn.execute(
                    "SELECT v FROM chaos")]
            finally:
                conn.close()
            missing = [v for v in acked if v not in set(rows)]
            if missing:
                raise InvariantViolation(
                    f"node {i + 1}: {len(missing)} acked writes missing "
                    f"from the SQLite state, e.g. {missing[:5]}")

    # -- flight bundle -------------------------------------------------

    def _flight_dump(self, err: BaseException) -> None:
        from raftsql_tpu.obs.flight import FlightRecorder
        bundle: dict = {"plan": self.plan.describe(),
                        "schedule_digest": self.plan.digest(),
                        "report": dict(self.report),
                        "acked": len(self.acked),
                        "logs": {}, "metrics": {}, "trace": {},
                        "wal_dirs": {}}
        for i in range(self.plan.peers):
            bundle["logs"][i] = self.cluster.log_tail(i)
            d = self.cluster.data_dir(i)
            try:
                bundle["wal_dirs"][i] = sorted(
                    f"{f} ({os.path.getsize(os.path.join(d, f))}B)"
                    for f in os.listdir(d))
            except OSError:
                bundle["wal_dirs"][i] = []
            if self.cluster.alive(i) and i not in self._stalled:
                try:
                    _, _, bundle["metrics"][i] = self.client.raw(
                        i, "GET", "/metrics", timeout_s=2.0)
                    _, _, bundle["trace"][i] = self.client.raw(
                        i, "GET", "/trace", timeout_s=2.0)
                except OSError:
                    pass
        FlightRecorder().dump(
            f"procs-seed{self.plan.seed}", repr(err), meta=bundle)

    # -- entry ---------------------------------------------------------

    def _verdict_digest(self) -> str:
        """Hash of what MUST reproduce across runs of one seed: the
        schedule, the per-invariant verdicts, and which fault families
        actually fired (booleans — counts are wall-clock-scheduled)."""
        r = self.report
        doc = {
            "schedule": self.plan.digest(),
            "invariants": dict(self.verdicts),
            "families": {
                "sigkill": r["kills"] >= len(self.plan.kills),
                "sigstop": r["stalls"] >= len(self.plan.stalls),
                "restart_storm": r["storm_restarts"]
                >= self.plan.peers * len(self.plan.storms),
                "enospc": r["fatal_exits"] >= 1,
                "exit_fsync": r["fsio_exits"] >= 1,
                "unexpected_exits": r["unexpected_exits"] == 0,
            },
        }
        blob = json.dumps(doc, sort_keys=True,
                          separators=(",", ":")).encode()
        return hashlib.sha256(blob).hexdigest()[:16]

    def _run_impl(self) -> dict:
        wt = threading.Thread(target=self._workload, daemon=True,
                              name="proc-chaos-workload")
        try:
            self._boot()
            wt.start()
            try:
                self._script()
            finally:
                self._stop_workload.set()
                wt.join(timeout=30)
            self.verdicts["single_leader"] = "pass"   # observe() raised
            self._converge()
            self.verdicts["convergence"] = "pass"
            self.verdicts["exactly_once"] = "pass"
            codes = self.cluster.stop_all()
            self.report["graceful_stops"] += sum(
                1 for c in codes if c == 0)
            self._post_mortem()
            self.verdicts["durability"] = "pass"
        except BaseException as e:
            self._stop_workload.set()
            self._flight_dump(e)
            raise
        finally:
            self.cluster.stop_all()
        self.report["acked"] = len(self.acked)
        return {"schedule_digest": self.plan.digest(),
                "result_digest": self._verdict_digest(),
                "seed": self.plan.seed, **self.report}

    def run(self) -> dict:
        return self._run_impl()


class ProcReadChaosRunner(ProcChaosRunner):
    """The process-plane read nemesis (`make chaos-reads`): the same
    seeded nemesis script (SIGKILLs, SIGSTOP stalls, restart storms,
    env disk faults) over real server processes, with the write
    workload replaced by a KV register workload that races every HTTP
    read mode against it through the hardened client:

      * linear GETs (X-Consistency: linear — lease or ReadIndex
        serves them engine-side, 421 redirects chased) checked by the
        thread-safe real-time register-linearizability invariant;
      * session GETs at RANDOM nodes presenting the X-Raft-Session
        watermark the last acked write returned — the answer must be
        at least as fresh as that write (read-your-writes across
        failover);
      * follower GETs (X-Consistency: follower) at random nodes —
        freshness floor = that replica's commit watermark, checked for
        monotonicity per key via the session rule.

    One sequential workload thread keeps the real-time order trivially
    sound (an op completes before the next is invoked).  Verdict
    digest extends the base families with the read families; counts
    stay wall-clock-scheduled, so the digest carries booleans."""

    KEYS = 4

    def __init__(self, plan: ProcChaosPlan, workdir: str,
                 http_engine: str = "aio"):
        super().__init__(plan, workdir, http_engine=http_engine)
        self.lin = RegisterLinearizability()
        # key -> (last acked value seq, its session watermark).
        self._sess: Dict[str, tuple] = {}
        self.report.update({"linear_reads": 0, "session_reads": 0,
                            "follower_reads": 0, "stale_session": 0})

    def _boot(self) -> None:
        super()._boot()
        create_deadline = time.monotonic() + 60.0
        while True:
            try:
                self.client.put(
                    "CREATE TABLE IF NOT EXISTS kv "
                    "(k text PRIMARY KEY, v text)", deadline_s=15.0)
                return
            except (SQLError, Unavailable):
                if time.monotonic() > create_deadline:
                    raise
                time.sleep(0.5)

    def _workload(self) -> None:
        """Sequential PUT/linear-GET/session-GET/follower-GET cycle:
        unique values per key (the register checker's contract), one
        retry token per logical write so crash-retries stay
        exactly-once."""
        import random
        rng = random.Random(self.plan.seed ^ 0x4EAD)
        n = 0
        while not self._stop_workload.is_set():
            try:
                key = f"k{rng.randrange(self.KEYS)}"
                val = f"w{n}"
                n += 1
                self.lin.begin_write(key, val)
                try:
                    wm = self.client.put(
                        "INSERT INTO kv (k, v) VALUES "
                        f"('{key}', '{val}') ON CONFLICT(k) "
                        f"DO UPDATE SET v='{val}'", deadline_s=8.0)
                except (SQLError, Unavailable):
                    pass      # unacked: may still commit later (legal)
                else:
                    self.lin.end_write(val)
                    with self._acked_lock:
                        self.acked.append(val)
                    self._sess[key] = (n - 1, wm or 0)
                self._read_cycle(rng)
            except BaseException as e:   # noqa: BLE001 - surfaced by
                self._workload_err = e   # _script (incl. violations)
                return
            time.sleep(0.05)

    def _read_cycle(self, rng) -> None:
        sel = "SELECT v FROM kv WHERE k='{}'"
        # Linear read: full register linearizability, any entry node
        # (421s chase the leader hint inside the client).
        key = f"k{rng.randrange(self.KEYS)}"
        h = self.lin.begin_read(key, mode="linear")
        try:
            rows = self.client.get(sel.format(key), linear=True,
                                   deadline_s=8.0)
        except (SQLError, Unavailable):
            pass              # no answer: read never happened
        else:
            self.lin.end_read(h, rows.strip().strip("|"))
            self.report["linear_reads"] += 1
        # Session read: the last acked write's watermark must be
        # visible from ANY node.
        if self._sess:
            key = rng.choice(sorted(self._sess))
            seq, wm = self._sess[key]
            node = rng.randrange(self.plan.peers)
            try:
                rows = self.client.get(sel.format(key), node=node,
                                       consistency="session",
                                       session=wm, deadline_s=8.0)
            except (SQLError, Unavailable):
                pass
            else:
                self.report["session_reads"] += 1
                got = rows.strip().strip("|")
                if not got or (got.startswith("w")
                               and got[1:].isdigit()
                               and int(got[1:]) < seq):
                    self.report["stale_session"] += 1
                    raise InvariantViolation(
                        f"session read({key!r}, wm={wm}) at node "
                        f"{node} returned {got!r}, older than acked "
                        f"write w{seq}")
        # Follower read: replica-commit freshness, any node.
        key = f"k{rng.randrange(self.KEYS)}"
        node = rng.randrange(self.plan.peers)
        try:
            self.client.get(sel.format(key), node=node,
                            consistency="follower", deadline_s=8.0)
        except (SQLError, Unavailable):
            pass
        else:
            self.report["follower_reads"] += 1

    def _converge(self, deadline_s: float = 60.0) -> List[str]:
        """Every node must answer the full ordered KV table
        identically, with each key at least as fresh as its last ACKED
        write (an unacked trailing write may legally have landed too —
        upserts overwrite, so exact-set equality is the wrong ask)."""
        want = {k: seq for k, (seq, _wm) in self._sess.items()}
        query = "SELECT k, v FROM kv ORDER BY k"
        deadline = time.monotonic() + deadline_s
        last: object = None
        while time.monotonic() < deadline:
            answers = []
            try:
                for i in range(self.plan.peers):
                    answers.append(self.client.get(
                        query, node=i, deadline_s=10.0))
            except (Unavailable, SQLError) as e:
                last = e
                time.sleep(0.5)
                continue
            if all(a == answers[0] for a in answers):
                rows = {}
                for line in answers[0].splitlines():
                    parts = line.strip("|").split("|")
                    if len(parts) == 2:
                        rows[parts[0]] = parts[1]
                stale = {
                    k: (rows.get(k), s) for k, s in want.items()
                    if not (rows.get(k, "").startswith("w")
                            and rows[k][1:].isdigit()
                            and int(rows[k][1:]) >= s)}
                if not stale:
                    return answers[0].splitlines()
                last = ("stale", stale)
            else:
                last = [len(a.splitlines()) for a in answers]
            time.sleep(0.5)
        raise InvariantViolation(
            f"KV convergence failed before the deadline; last={last!r}")

    def _post_mortem(self) -> None:
        """Durability from DISK alone, upsert-aware: replay every
        node's WAL, fold the committed (post-dedup) upserts per key in
        order, and require (a) every node folds to the SAME final KV,
        (b) each key at least as fresh as its last acked write, and
        (c) each node's cold-opened SQLite kv table matches its own
        fold."""
        import re
        from raftsql_tpu.runtime.envelope import unwrap
        from raftsql_tpu.storage.wal import WAL
        pat = re.compile(r"VALUES \('(k\d+)', '(w\d+)'\)")
        want = {k: seq for k, (seq, _wm) in self._sess.items()}
        folds = []
        for i in range(self.plan.peers):
            groups = WAL.replay(self.cluster.data_dir(i))
            gl = groups.get(0)
            if gl is None:
                raise InvariantViolation(
                    f"node {i + 1}: WAL replay has no group 0")
            committed = gl.entries[:max(0, gl.hard.commit - gl.start)]
            seen_pids: Set[int] = set()
            kv: Dict[str, str] = {}
            for (_term, data) in committed:
                if not data:
                    continue
                pid, payload = unwrap(data)
                if pid is not None:
                    if pid in seen_pids:
                        continue
                    seen_pids.add(pid)
                m = pat.search(payload.decode("utf-8", "replace"))
                if m:
                    kv[m.group(1)] = m.group(2)
            folds.append(kv)
            for k, s in want.items():
                got = kv.get(k, "")
                if not (got.startswith("w") and got[1:].isdigit()
                        and int(got[1:]) >= s):
                    raise InvariantViolation(
                        f"node {i + 1}: key {k} folded to {got!r} in "
                        f"the committed WAL prefix — staler than "
                        f"acked w{s}")
            conn = sqlite3.connect(self.cluster.db_path(i))
            try:
                rows = dict(conn.execute("SELECT k, v FROM kv"))
            finally:
                conn.close()
            if rows != kv:
                raise InvariantViolation(
                    f"node {i + 1}: SQLite kv {rows!r} diverges from "
                    f"its committed WAL fold {kv!r}")
        if any(f != folds[0] for f in folds[1:]):
            raise InvariantViolation(
                f"nodes folded to different committed KV states: "
                f"{folds!r}")

    def _verdict_digest(self) -> str:
        """What must reproduce for the READ nemesis: the schedule, the
        invariant verdicts, and the read families.  The base runner's
        storage-fault booleans are deliberately excluded — their op
        thresholds accumulate with the wall-clock-paced workload, and
        whether they fire inside the window is kernel-scheduled (the
        signal nemesis families are guaranteed by the script's
        deferral loop and asserted by the gate instead)."""
        import hashlib as _h
        import json as _j
        r = self.report
        doc = {
            "schedule": self.plan.digest(),
            "invariants": dict(self.verdicts),
            "read_families": {
                "linear": r["linear_reads"] > 0,
                "session": r["session_reads"] > 0,
                "follower": r["follower_reads"] > 0,
                "stale_session": r["stale_session"] == 0,
                "unexpected_exits": r["unexpected_exits"] == 0,
            },
        }
        blob = _j.dumps(doc, sort_keys=True,
                        separators=(",", ":")).encode()
        return _h.sha256(blob).hexdigest()[:16]

    def run(self) -> dict:
        out = self._run_impl()
        out["result_digest"] = self._verdict_digest()
        return out


class ProcTransferChaosRunner(ProcChaosRunner):
    """Transfer-under-nemesis on the PROCESS plane (`make
    chaos-transfer`): the same seeded nemesis script (SIGKILLs,
    SIGSTOP stalls, restart storms, env disk faults) over real server
    processes, with the acked-PUT workload interleaving graceful
    leadership transfers driven through the public admin surface —
    `POST /transfer` at whoever /healthz says leads group 0, then
    polling /healthz until leadership lands on the requested target.

    Reuses ProcChaosPlan unchanged (extending it would move every
    existing proc-family digest).  A transfer outstanding when the
    nemesis kills the leader is LOST (the latch dies with the process)
    — counted, not failed: availability through it all is what the
    acked-PUT stream plus convergence and the WAL post-mortem already
    assert.  Verdict digest carries transfer-family booleans (counts
    are wall-clock-paced)."""

    XFER_EVERY = 20          # workload iterations between attempts
    XFER_DEADLINE_S = 25.0   # generous: spans a stall + re-election

    def __init__(self, plan: ProcChaosPlan, workdir: str,
                 http_engine: str = "aio"):
        super().__init__(plan, workdir, http_engine=http_engine)
        self.report.update({
            "transfers_requested": 0, "transfers_completed": 0,
            "transfers_refused": 0, "transfers_lost": 0,
        })

    def _workload(self) -> None:
        import random
        rng = random.Random(self.plan.seed ^ 0x7AFE)
        pending = None           # (target slot, wall deadline)
        n = 0
        while not self._stop_workload.is_set():
            val = f"w{n}"
            n += 1
            try:
                self.client.put(
                    f"INSERT INTO chaos (v) VALUES ('{val}')",
                    deadline_s=8.0)
                with self._acked_lock:
                    self.acked.append(val)
            except (SQLError, Unavailable):
                pass
            except BaseException as e:   # noqa: BLE001 - surfaced
                self._workload_err = e
                return
            if n % self.XFER_EVERY == 0 or pending is not None:
                try:
                    pending = self._transfer_cycle(rng, pending,
                                                   issue=n %
                                                   self.XFER_EVERY == 0)
                except BaseException as e:   # noqa: BLE001 - surfaced
                    self._workload_err = e
                    return
            time.sleep(0.08)

    def _transfer_cycle(self, rng, pending, issue: bool):
        """One observation of the transfer state machine: resolve the
        group-0 leader from /healthz, settle an outstanding request
        (completed / lost), and maybe issue a new one."""
        docs = self._healthz_all()
        lead = None
        for i, doc in sorted(docs.items()):
            if doc and doc["groups"].get("0", {}).get("role") == _LEADER:
                lead = i
                break
        if pending is not None:
            target, dl = pending
            if lead == target:
                self.report["transfers_completed"] += 1
                return None
            if time.monotonic() > dl:
                # Engine abort, or the latch died with a killed
                # leader: either way the group kept serving (the PUT
                # stream asserts that) — log and move on.
                self.report["transfers_lost"] += 1
                return None
            return pending
        if not issue or lead is None:
            return None
        target = (lead + 1
                  + rng.randrange(self.plan.peers - 1)) % self.plan.peers
        try:
            status, _hdrs, _text = self.client.raw(
                lead, "POST", "/transfer",
                body=json.dumps({"group": 0, "target": target}),
                timeout_s=3.0)
        except OSError:
            return None              # leader died under us: next cycle
        if status == 200:
            self.report["transfers_requested"] += 1
            return (target, time.monotonic() + self.XFER_DEADLINE_S)
        # 400 = engine refusal (latch in flight, learner target);
        # 421 = our /healthz view was stale — both retry next cycle.
        self.report["transfers_refused"] += 1
        return None

    def _verdict_digest(self) -> str:
        """What must reproduce for the transfer nemesis: the schedule,
        the invariant verdicts, and the transfer-family booleans.  The
        base storage-fault booleans are excluded for the same reason
        ProcReadChaosRunner excludes them — their op thresholds
        accumulate with the wall-clock-paced workload."""
        r = self.report
        doc = {
            "schedule": self.plan.digest(),
            "invariants": dict(self.verdicts),
            "transfer_families": {
                "requested": r["transfers_requested"] > 0,
                "completed": r["transfers_completed"] > 0,
                "unexpected_exits": r["unexpected_exits"] == 0,
            },
        }
        blob = json.dumps(doc, sort_keys=True,
                          separators=(",", ":")).encode()
        return hashlib.sha256(blob).hexdigest()[:16]

    def run(self) -> dict:
        out = self._run_impl()
        out["result_digest"] = self._verdict_digest()
        return out
