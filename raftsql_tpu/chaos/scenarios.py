"""Chaos scenario runners: drive a live engine through a seeded
`ChaosSchedule`, injecting faults at every seam, checking invariants
every tick.

Two runners, matching the two deployment shapes:

  * `FusedChaosRunner` — the fused single-dispatch runtime
    (runtime/fused.py FusedClusterNode).  Fully deterministic: one
    thread drives `tick()` manually, fault masks are host-generated
    from the schedule's seed, crashes are simulated in-process, and
    the run's result digest is reproducible bit-for-bit from the seed
    (`make chaos` proves it by running a seed twice).
  * `NodeClusterChaosRunner` — the threaded/distributed runtime
    (runtime/node.py RaftNode) as a LOCKSTEP cluster over the loopback
    transport: per-node crash/restart, leader-targeted kills, and
    FaultPlan partitions, with per-node durability and cross-node log
    matching checked from the commit streams.

Crash simulation ("hard crash"): every open durable fd of the dying
node is redirected to /dev/null before the object is abandoned — a
buffered-but-unflushed byte can then never be resurrected by a later
GC flush into the file the restarted node is appending to.  That IS a
process kill's semantics (userspace buffers lost, flushed page-cache
bytes kept).  A POWER LOSS additionally truncates every file to its
last really-fsynced size, optionally tearing one peer's last record
mid-write (storage/fsio.py records both) — which is exactly the state
WAL._repair_tail and the epoch-repair path exist to recover.
"""
from __future__ import annotations

import hashlib
import json
import os
import queue
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax.numpy as jnp

from raftsql_tpu.chaos.invariants import (CommitMonotonic,
                                          DurabilityLedger, ElectionSafety,
                                          InvariantViolation,
                                          RegisterLinearizability,
                                          check_log_matching)
from raftsql_tpu.chaos.schedule import (LEADER_TARGET, ChaosSchedule,
                                        NodeChaosPlan)
from raftsql_tpu.config import LEADER, RaftConfig
from raftsql_tpu.runtime.db import _expand_commit_item, iter_plain_batches
from raftsql_tpu.runtime.fused import FusedClusterNode
from raftsql_tpu.runtime.node import CLOSED, RaftNode
from raftsql_tpu.storage import fsio
from raftsql_tpu.transport.faults import (drop_messages, hold_messages,
                                          partition_peer, release_messages)
from raftsql_tpu.transport.loopback import LoopbackHub, LoopbackTransport

DEAD_ROLE = -1          # role code for a crashed node's safety-matrix row


def _redirect_to_devnull(files) -> None:
    """dup2 /dev/null over every open fd so abandoned buffered writers
    can never flush real bytes later."""
    devnull = os.open(os.devnull, os.O_WRONLY)
    try:
        for f in files:
            if f is not None and not f.closed:
                os.dup2(devnull, f.fileno())
    finally:
        os.close(devnull)


def hard_crash_fused(node: FusedClusterNode) -> None:
    """Simulate a process kill of the whole fused cluster process.

    Requires the Python WAL backend (an installed fsio injector forces
    it): the native backend buffers inside C++ where this simulation
    cannot reach."""
    _redirect_to_devnull([getattr(w, "_f", None) for w in node.wals]
                         + [node._epoch_f])
    # Unblock the publisher worker so the abandoned daemon thread exits
    # instead of leaking one thread per simulated crash.
    try:
        node._pub_q.put_nowait(None)
    except queue.Full:                   # pragma: no cover - bounded lag
        pass


def hard_crash_node(node: RaftNode) -> None:
    """Simulate a process kill of one RaftNode: WAL fd neutered, then
    detached from the loopback hub (its 'NIC' goes dark)."""
    _redirect_to_devnull([getattr(node.wal, "_f", None)])
    node.transport.stop()


def _power_loss(inj: fsio.StorageFaultInjector, data_dir: str,
                tear_peer: int = -1) -> Tuple[int, int]:
    """Apply power-loss semantics to every tracked file under data_dir:
    drop everything after the last real fsync, tearing (keeping a
    partial prefix of) the tear peer's last unsynced record instead of
    dropping it whole.  Returns (files_truncated, records_torn)."""
    torn = dropped = 0
    tear_paths = set()
    if tear_peer >= 0:
        tag = os.sep + f"p{tear_peer + 1}" + os.sep
        for path in inj.tracked_paths():
            if path.startswith(data_dir) and tag in path \
                    and inj.tear_last_write(path):
                torn += 1
                tear_paths.add(path)
    for path in inj.tracked_paths():
        if path.startswith(data_dir) and path not in tear_paths \
                and inj.drop_unsynced(path):
            dropped += 1
    return dropped, torn


def _drain_fused_q(q: "queue.Queue") -> List[Tuple[int, int, List[bytes]]]:
    """Drain a fused commit queue non-blocking into plain
    (group, base_idx, [payload, ...]) batches (sentinels skipped)."""
    batches: List[Tuple[int, int, List[bytes]]] = []
    while True:
        try:
            item = q.get_nowait()
        except queue.Empty:
            return batches
        if item is None:
            continue
        if item is CLOSED:
            return batches
        batches.extend(iter_plain_batches(item))


class FusedChaosRunner:
    """Drive a FusedClusterNode through a ChaosSchedule.

    Workload: seeded unique-value PUTs (`SET k<K> v<seq>`) routed by
    key to a group, plus linearizable GETs registered through
    `read_index` and resolved against peer 0's applied state.  Every
    tick: release due delayed messages, apply the tick's fault masks,
    issue workload, dispatch, flush+drain publishes, resolve reads,
    observe invariants.  Crashes (scheduled, or triggered by an
    injected fsync failure) restart the cluster from its WALs and
    verify the durability ledger against the replay.
    """

    KEYS = 8
    LOG_MATCH_EVERY = 16

    def __init__(self, schedule: ChaosSchedule, data_dir: str,
                 cfg: Optional[RaftConfig] = None, steps: int = 1):
        self.sched = schedule
        self.data_dir = data_dir
        self.cfg = cfg or RaftConfig(
            num_groups=4, num_peers=schedule_peers(schedule),
            log_window=64, max_entries_per_msg=4, election_ticks=10,
            heartbeat_ticks=1, tick_interval_s=0.0)
        self.steps = steps
        self.node: Optional[FusedClusterNode] = None
        self.ledger = DurabilityLedger()
        self.lin = RegisterLinearizability()
        self.safety = ElectionSafety(LEADER)
        self.monotonic = CommitMonotonic(self.cfg.num_peers,
                                         self.cfg.num_groups)
        self._kv: Dict[str, str] = {}
        self._applied = np.zeros(self.cfg.num_groups, np.int64)
        self._held: List[Tuple[int, object]] = []
        self._pending_reads: List[Tuple[str, int, int, tuple]] = []
        self._part_peer: Dict[int, int] = {}
        self._wseq = 0
        self.report: Dict[str, int] = {
            "crashes": 0, "restarts": 0, "partitions": 0,
            "fsync_faults": 0, "torn_write_faults": 0, "torn_writes": 0,
            "unsynced_files_dropped": 0, "dropped_slots": 0,
            "delayed_slots": 0, "log_match_checks": 0,
        }

    # -- lifecycle -----------------------------------------------------

    def _boot(self, first: bool) -> FusedClusterNode:
        node = FusedClusterNode(self.cfg, self.data_dir,
                                seed=self.sched.seed)
        if self.steps > 1:
            node._steps = self.steps
        node.publish_peers = {0}
        replayed: Dict[Tuple[int, int], bytes] = {}
        order: List[Tuple[int, int, bytes]] = []
        for p in range(self.cfg.num_peers):
            for (g, base, datas) in _drain_fused_q(node.commit_q(p)):
                if p != 0:
                    continue             # peer 0's stream is the client
                for off, d in enumerate(datas):
                    if d:
                        replayed[(g, base + 1 + off)] = d
                        order.append((g, base + 1 + off, d))
        if not first:
            self.ledger.verify_replay(
                replayed, context=f"restart {self.report['restarts']}")
            self.report["restarts"] += 1
        # Rebuild the client-visible KV state from the replay (per-group
        # index order; groups are independent key spaces).
        self._kv.clear()
        for g, i, d in sorted(order):
            self._apply(g, i, d)
        self._applied = node._applied[0].copy()
        node.metrics.faults_crashes = self.report["crashes"]
        return node

    def _crash_restart(self, tick: int, power_loss: bool = False,
                       tear_peer: int = -1) -> None:
        hard_crash_fused(self.node)
        self.report["crashes"] += 1
        if power_loss:
            inj = fsio.injector()
            dropped, torn = _power_loss(inj, self.data_dir, tear_peer)
            self.report["unsynced_files_dropped"] += dropped
            self.report["torn_writes"] += torn
        # In-flight state dies with the process: delayed messages and
        # registered-but-unresolved reads (their clients aborted).
        self._held.clear()
        self._pending_reads.clear()
        self.node = self._boot(first=False)

    # -- workload ------------------------------------------------------

    def _apply(self, g: int, idx: int, payload: bytes) -> None:
        self.ledger.record(g, idx, payload)
        parts = payload.decode("utf-8").split(" ")
        if len(parts) == 3 and parts[0] == "SET":
            self._kv[parts[1]] = parts[2]
            self.lin.end_write(parts[2])
        self._applied[g] = max(self._applied[g], idx)

    def _issue(self, rng: np.random.Generator) -> None:
        if rng.random() < self.sched.prop_rate:
            k = int(rng.integers(0, self.KEYS))
            g = k % self.cfg.num_groups
            value = f"v{self._wseq}"
            self._wseq += 1
            self.lin.begin_write(f"k{k}", value)
            self.node.propose_many(g, [f"SET k{k} {value}".encode()])
        if rng.random() < self.sched.read_rate:
            k = int(rng.integers(0, self.KEYS))
            g = k % self.cfg.num_groups
            got = self.node.read_index(g)
            if got:                       # leaderless: client retries later
                target, _ = got
                self._pending_reads.append(
                    (f"k{k}", g, target, self.lin.begin_read(f"k{k}")))

    def _resolve_reads(self) -> None:
        still = []
        for (key, g, target, handle) in self._pending_reads:
            if self._applied[g] >= target:
                self.lin.end_read(handle, self._kv.get(key, ""))
            else:
                still.append((key, g, target, handle))
        self._pending_reads = still

    # -- fault application ---------------------------------------------

    def _apply_faults(self, t: int, rng: np.random.Generator) -> None:
        node = self.node
        due = [h for (rt, h) in self._held if rt <= t]
        self._held = [(rt, h) for (rt, h) in self._held if rt > t]
        for h in due:                    # released mail is subject to
            node.inboxes = release_messages(node.inboxes, h)  # this
        shape = node.inboxes.v_type.shape          # tick's masks below
        for w in self.sched.delays:
            if w.start <= t < w.end:
                mask = rng.random(shape) < w.p
                if mask.any():
                    delivered, held = hold_messages(node.inboxes,
                                                    jnp.asarray(mask))
                    node.inboxes = delivered
                    self._held.append((t + w.latency, held))
                    self.report["delayed_slots"] += int(mask.sum())
        for w in self.sched.drops:
            if w.start <= t < w.end:
                mask = rng.random(shape) < w.p
                if mask.any():
                    node.inboxes = drop_messages(node.inboxes,
                                                 jnp.asarray(mask))
                    self.report["dropped_slots"] += int(mask.sum())
        for wi, w in enumerate(self.sched.partitions):
            if w.start <= t < w.end:
                peer = self._part_peer.get(wi)
                if peer is None:
                    peer = w.peer if w.peer >= 0 \
                        else max(self.node.leader_of(0), 0)
                    self._part_peer[wi] = peer
                    self.report["partitions"] += 1
                node.inboxes = partition_peer(node.inboxes, peer)

    # -- invariants ----------------------------------------------------

    def _observe(self, t: int) -> None:
        node = self.node
        roles = node.roles()
        terms = np.asarray(node.states.term)
        self.safety.observe(t, roles, terms)
        commits = node._hard[:, :, 2]
        self.monotonic.observe(t, commits)
        if t % self.LOG_MATCH_EVERY == 0:
            check_log_matching(t, commits, node.plogs)
            self.report["log_match_checks"] += 1

    # -- the run -------------------------------------------------------

    def run(self) -> dict:
        inj = fsio.StorageFaultInjector()
        for f in self.sched.fsync_faults:
            inj.add_rule(os.sep + f"p{f.peer + 1}" + os.sep,
                         fail_at=(f.op,))
        for f in self.sched.torn_writes:
            inj.add_rule(os.sep + f"p{f.peer + 1}" + os.sep,
                         crash_write_at=(f.op,), tag=f.peer)
        crash_at = {ev.tick: ev for ev in self.sched.crashes}
        rng = np.random.default_rng(self.sched.seed + 1)
        with fsio.installed(inj):
            self.node = self._boot(first=True)
            try:
                for t in range(self.sched.ticks):
                    ev = crash_at.get(t)
                    if ev is not None:
                        self._crash_restart(t, ev.power_loss,
                                            ev.tear_peer)
                    self._apply_faults(t, rng)
                    self._issue(rng)
                    try:
                        self.node.tick()
                    except fsio.FsyncFaultError:
                        # etcd posture: a failed WAL fsync is fatal —
                        # crash the process rather than ack unsynced
                        # data; the restart replays the durable prefix.
                        self.report["fsync_faults"] += 1
                        self._crash_restart(t, power_loss=False)
                        continue
                    except fsio.CrashPointError as e:
                        # Power loss mid-record: the machine dies with
                        # the record partially written and the tick's
                        # barrier never reached — tear that record,
                        # drop every unsynced tail, restart.
                        self.report["torn_write_faults"] += 1
                        self._crash_restart(t, power_loss=True,
                                            tear_peer=int(e.tag))
                        continue
                    self.node.publish_flush()
                    for (g, base, datas) in _drain_fused_q(
                            self.node.commit_q(0)):
                        for off, d in enumerate(datas):
                            if d:
                                self._apply(g, base + 1 + off, d)
                    self._applied = np.maximum(self._applied,
                                               self.node._applied[0])
                    self._resolve_reads()
                    self._observe(t)
                # Final deep checks + a restart pass so the run always
                # ends with a full durability audit.
                check_log_matching(self.sched.ticks,
                                   self.node._hard[:, :, 2],
                                   self.node.plogs)
                self.report["log_match_checks"] += 1
                self._crash_restart(self.sched.ticks)
                m = self.node.metrics
                m.faults_dropped_msgs = self.report["dropped_slots"]
                m.faults_delayed_msgs = self.report["delayed_slots"]
                m.faults_partitions = self.report["partitions"]
                m.faults_fsync = self.report["fsync_faults"]
            finally:
                node, self.node = self.node, None
                if node is not None:
                    node.stop()
        return self._report()

    def _report(self) -> dict:
        committed = sorted(
            (g, i, d.decode("utf-8"))
            for (g, i), d in self.ledger._committed.items())
        blob = json.dumps(
            {"committed": committed, "report": self.report,
             "writes": self._wseq, "reads": self.lin.reads_checked},
            sort_keys=True, separators=(",", ":")).encode()
        return {
            "seed": self.sched.seed,
            "ticks": self.sched.ticks,
            "schedule_digest": self.sched.digest(),
            "result_digest": hashlib.sha256(blob).hexdigest()[:16],
            "committed_entries": len(self.ledger),
            "writes_issued": self._wseq,
            "reads_checked": self.lin.reads_checked,
            "safety_observations": self.safety.observations,
            **self.report,
        }


def schedule_peers(schedule: ChaosSchedule) -> int:
    """Peer count implied by a schedule's targets (min 3)."""
    peers = 3
    for w in schedule.partitions:
        peers = max(peers, w.peer + 1)
    for ev in schedule.crashes:
        peers = max(peers, ev.tear_peer + 1)
    for f in schedule.fsync_faults:
        peers = max(peers, f.peer + 1)
    return peers


class NodeClusterChaosRunner:
    """Lockstep RaftNode cluster under a NodeChaosPlan.

    P RaftNodes over the loopback transport, ticked manually in id
    order (deterministic consensus schedule; envelope ids randomize WAL
    bytes but not the schedule).  Faults: FaultPlan partitions,
    per-node hard crash + restart-from-WAL, leader-targeted kills.
    Invariants: election safety, per-node commit-stream durability
    across restart, and cross-node log matching of live-published
    (committed) entries.
    """

    def __init__(self, plan: NodeChaosPlan, tmpdir: str,
                 cfg: Optional[RaftConfig] = None, peers: int = 3):
        self.plan = plan
        self.tmpdir = tmpdir
        self.P = peers
        self.cfg = cfg or RaftConfig(
            num_groups=2, num_peers=peers, log_window=64,
            max_entries_per_msg=4, election_ticks=10, heartbeat_ticks=1,
            tick_interval_s=0.0)
        self.hub = LoopbackHub()
        self.nodes: List[Optional[RaftNode]] = [None] * peers
        self.safety = ElectionSafety(LEADER)
        self.monotonic = CommitMonotonic(peers, self.cfg.num_groups)
        # Live-published (committed) history, shared: (g, idx) -> sql.
        self._hist: Dict[Tuple[int, int], str] = {}
        # Per node: everything IT has published live (must survive its
        # own restarts).
        self._published: List[Dict[Tuple[int, int], str]] = [
            {} for _ in range(peers)]
        self.report = {"crashes": 0, "restarts": 0, "partitions": 0,
                       "commits": 0}

    def _data_dir(self, p: int) -> str:
        return os.path.join(self.tmpdir, f"chaos-node-{p + 1}")

    def _boot(self, p: int) -> RaftNode:
        n = RaftNode(p + 1, self.P, self.cfg,
                     LoopbackTransport(self.hub), self._data_dir(p))
        n.start(threaded=False)
        # Replay drain: every WAL entry then the nil sentinel
        # (raft.go:122-134).  Verify durability of everything this node
        # ever acked; do NOT fold replay into the shared history —
        # replay includes uncommitted entries that may legally be
        # conflict-truncated later.
        replayed: Dict[Tuple[int, int], str] = {}
        while True:
            try:
                item = n.commit_q.get_nowait()
            except queue.Empty:
                break
            if item is None:
                break
            if item is CLOSED:
                break
            for (g, idx, sql) in _expand_commit_item(item, n):
                replayed[(g, idx)] = sql
        for (g, idx), sql in self._published[p].items():
            got = replayed.get((g, idx))
            if got != sql:
                raise InvariantViolation(
                    f"node {p}: committed entry g{g} i{idx} "
                    f"{'lost' if got is None else 'changed'} across "
                    f"restart")
        return n

    def _resolve(self, peer: int) -> int:
        if peer != LEADER_TARGET:
            return peer
        for n in self.nodes:
            if n is not None and n.leader_of(0) >= 0:
                return int(n.leader_of(0))
        return 0

    def _drain_live(self) -> None:
        for p, n in enumerate(self.nodes):
            if n is None:
                continue
            while True:
                try:
                    item = n.commit_q.get_nowait()
                except queue.Empty:
                    break
                if item is None or item is CLOSED:
                    continue
                for (g, idx, sql) in _expand_commit_item(item, n):
                    prev = self._hist.setdefault((g, idx), sql)
                    if prev != sql:
                        raise InvariantViolation(
                            f"log matching: node {p} committed g{g} "
                            f"i{idx} {sql!r} but {prev!r} was committed")
                    self._published[p][(g, idx)] = sql
                    self.report["commits"] += 1

    def _observe(self, t: int) -> None:
        G = self.cfg.num_groups
        roles = np.full((self.P, G), DEAD_ROLE, np.int64)
        terms = np.zeros((self.P, G), np.int64)
        commits = np.zeros((self.P, G), np.int64)
        for p, n in enumerate(self.nodes):
            if n is None:
                continue
            roles[p] = n._last_role
            terms[p] = n._hard_np[:, 0]
            commits[p] = n._hard_np[:, 2]
        self.safety.observe(t, roles, terms)
        # Dead rows read 0 — mask them to each node's running floor so
        # a down node never looks like a regression.
        commits = np.maximum(commits, self.monotonic._hi * (roles < 0))
        self.monotonic.observe(t, commits)

    def run(self) -> dict:
        inj = fsio.StorageFaultInjector()   # no rules: forces the
        rng = np.random.default_rng(self.plan.seed + 1)  # python WAL
        crash_at: Dict[int, list] = {}
        for c in self.plan.crashes:
            crash_at.setdefault(c.tick, []).append(c)
        down_until: Dict[int, int] = {}
        with fsio.installed(inj):
            for p in range(self.P):
                self.nodes[p] = self._boot(p)
            try:
                for t in range(self.plan.ticks):
                    for c in crash_at.get(t, ()):
                        p = self._resolve(c.peer)
                        if self.nodes[p] is None:
                            continue
                        hard_crash_node(self.nodes[p])
                        self.nodes[p] = None
                        down_until[p] = t + c.down
                        self.report["crashes"] += 1
                    for p in [p for p, d in down_until.items()
                              if d <= t]:
                        del down_until[p]
                        self.nodes[p] = self._boot(p)
                        self.report["restarts"] += 1
                    self.hub.faults.heal()
                    for w in self.plan.partitions:
                        if w.start <= t < w.end:
                            if t == w.start:
                                self.report["partitions"] += 1
                            self.hub.faults.isolate(
                                w.peer + 1, range(1, self.P + 1))
                    if rng.random() < self.plan.prop_rate:
                        alive = [p for p, n in enumerate(self.nodes)
                                 if n is not None]
                        src = alive[int(rng.integers(0, len(alive)))]
                        g = int(rng.integers(0, self.cfg.num_groups))
                        self.nodes[src].propose(
                            g, f"SET k{g} v{t}".encode())
                    for n in self.nodes:
                        if n is not None:
                            n.tick()
                    self._drain_live()
                    self._observe(t)
            finally:
                for n in self.nodes:
                    if n is not None:
                        n.stop()
        return {"plan_digest": self.plan.digest(), **self.report}
